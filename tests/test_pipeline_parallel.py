"""Pipeline parallelism: the GPipe schedule over an 8-device CPU mesh must
exactly reproduce the sequential layer stack — values and gradients
(the same n-device == 1-device contract as the DP/TP/SP tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.pipeline import (
    STAGE_AXIS,
    pipeline_apply,
    pipeline_parallel_mesh,
    sequential_apply,
    shard_stage_params,
)


def _dense_stage(params, x):
    return jnp.tanh(x @ params["W"] + params["b"])


def _stacked_dense(S, D, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "W": jnp.asarray(rng.standard_normal((S, D, D)) * (1.0 / np.sqrt(D)),
                         jnp.float32),
        "b": jnp.asarray(rng.standard_normal((S, D)) * 0.1, jnp.float32),
    }


@pytest.mark.parametrize("n_stages,n_microbatches", [(8, 8), (8, 4), (4, 16)])
def test_pipeline_matches_sequential(n_stages, n_microbatches):
    D, B = 16, 32
    devices = jax.devices()[:n_stages]
    mesh = pipeline_parallel_mesh(devices)
    params = shard_stage_params(_stacked_dense(n_stages, D), mesh)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((B, D)),
                    jnp.float32)

    got = pipeline_apply(_dense_stage, params, x, mesh=mesh,
                         n_microbatches=n_microbatches)
    want = sequential_apply(_dense_stage, params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    """Backward through the pipeline (the reverse schedule autodiff
    derives) must produce the sequential stack's gradients."""
    S, D, B, M = 4, 8, 16, 4
    mesh = pipeline_parallel_mesh(jax.devices()[:S])
    params = shard_stage_params(_stacked_dense(S, D, seed=2), mesh)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((B, D)),
                    jnp.float32)
    y = jnp.asarray(np.random.default_rng(4).standard_normal((B, D)),
                    jnp.float32)

    def loss_pipe(p):
        out = pipeline_apply(_dense_stage, p, x, mesh=mesh, n_microbatches=M)
        return jnp.mean((out - y) ** 2)

    def loss_seq(p):
        out = sequential_apply(_dense_stage, p, x)
        return jnp.mean((out - y) ** 2)

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in ("W", "b"):
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=2e-5, atol=1e-6)


def test_pipeline_jitted_train_step():
    """One jitted SGD step over the pipeline: params stay stage-sharded,
    loss decreases — the full training path a PP user runs."""
    S, D, B, M = 8, 8, 32, 8
    mesh = pipeline_parallel_mesh(jax.devices()[:S])
    params = shard_stage_params(_stacked_dense(S, D, seed=5), mesh)
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)

    @jax.jit
    def step(p):
        def loss(p):
            out = pipeline_apply(_dense_stage, p, x, mesh=mesh,
                                 n_microbatches=M)
            return jnp.mean((out - y) ** 2)

        l, g = jax.value_and_grad(loss)(p)
        return l, jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    l0, params = step(params)
    for _ in range(5):
        l1, params = step(params)
    assert float(l1) < float(l0)
    # stage sharding must survive the update (no silent gather)
    w = params["W"]
    assert w.sharding.shard_shape(w.shape)[0] == 1, (
        f"stage params gathered: {w.sharding}")


def test_pipeline_batch_not_divisible_raises():
    mesh = pipeline_parallel_mesh(jax.devices()[:4])
    params = shard_stage_params(_stacked_dense(4, 8), mesh)
    x = jnp.zeros((10, 8), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(_dense_stage, params, x, mesh=mesh, n_microbatches=4)
