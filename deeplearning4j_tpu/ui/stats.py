"""StatsListener: per-iteration training telemetry -> storage router.

Reference: BaseStatsListener.java:51,103-124 — collects score,
param/gradient/update mean magnitudes, learning rate, memory and
throughput counters each iteration and routes them through a
StatsStorageRouter; cadence controlled by StatsUpdateConfiguration.

TPU-first: the mean-magnitude reductions are fused INTO the jitted train
step (net.set_collect_stats(True) — netbase exposes them via
info["stats"]) so collection adds tiny on-device reductions instead of
host-side parameter sweeps; the host readback happens only every
``frequency`` iterations.
"""

from __future__ import annotations

import time
import uuid
from typing import Optional

import numpy as np

from deeplearning4j_tpu.train.listeners import IterationListener
from deeplearning4j_tpu.ui.storage import StatsStorageRouter


def _device_memory_stats() -> dict:
    """Per-device memory counters when the backend exposes them (TPU/GPU
    runtimes do; CPU returns nothing). Reference reports JVM/off-heap
    memory per device (BaseStatsListener memory section)."""
    import jax

    out = {}
    try:
        for d in jax.local_devices():
            ms = d.memory_stats()
            if ms:
                out[f"device{d.id}"] = {
                    "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                    "bytes_limit": int(ms.get("bytes_limit", 0)),
                }
    except Exception:
        pass
    return out


def split_stat_key(key: str):
    """Decode the '{layer_index}_{param_name}' keys StatsListener emits in
    grad_mm/update_mm/param_mm/hists records — the ONE place the format is
    known (consumers: ui/server.py, ui/report.py)."""
    li, _, pname = key.partition("_")
    return li, pname


def model_graph(model) -> dict:
    """Topology for the flow view (reference: FlowListenerModule's
    layer-graph payload): {nodes: [{id, label, layer_index?}], edges:
    [[src, dst], ...]}. ComputationGraphs expose their DAG; a
    MultiLayerNetwork is the input->layer0->...->layerN chain."""
    conf = getattr(model, "conf", None)
    confs = model._ordered_layer_confs()
    if hasattr(conf, "vertex_inputs"):  # ComputationGraph
        pidx = getattr(model, "_pidx", {})
        nodes = [{"id": n, "label": n} for n in conf.inputs]
        for name, v in conf.vertices.items():
            layer = getattr(v, "layer", None)
            nodes.append({
                "id": name,
                "label": f"{name}\n{type(layer or v).__name__}",
                **({"layer_index": pidx[name]} if name in pidx else {}),
            })
        edges = [[src, name]
                 for name, ins in conf.vertex_inputs.items()
                 for src in ins]
        return {"nodes": nodes, "edges": edges,
                "outputs": list(conf.outputs)}
    nodes = [{"id": "input", "label": "input"}]
    edges = []
    prev = "input"
    for i, c in enumerate(confs):
        nid = f"layer{i}"
        nodes.append({"id": nid, "label": f"{i}: {type(c).__name__}",
                      "layer_index": i})
        edges.append([prev, nid])
        prev = nid
    return {"nodes": nodes, "edges": edges, "outputs": [prev]}


class StatsListener(IterationListener):
    """Routes per-iteration stats to a StatsStorageRouter.

    Usage::

        storage = InMemoryStatsStorage()
        net.set_collect_stats(True)
        net.set_listeners(StatsListener(storage))
        net.fit(...)
        UIServer(storage).start()
    """

    def __init__(self, router: StatsStorageRouter,
                 session_id: Optional[str] = None,
                 worker_id: str = "worker0",
                 frequency: int = 1,
                 report_memory: bool = True,
                 histogram_bins: int = 0,
                 histogram_frequency: int = 10):
        self.router = router
        self.session_id = session_id or f"session-{uuid.uuid4().hex[:8]}"
        self.worker_id = worker_id
        self.frequency = max(1, int(frequency))
        self.report_memory = report_memory
        # >0 turns on per-layer parameter histograms (reference:
        # HistogramModule / weights histogram tab). Histograms force a
        # full-parameter device readback, so they ride every
        # `histogram_frequency`-th REPORT (i.e. every
        # frequency * histogram_frequency iterations).
        self.histogram_bins = int(histogram_bins)
        self.histogram_frequency = max(1, int(histogram_frequency))
        self._reports = 0
        self._sent_static = False
        self._last_time: Optional[float] = None
        self._samples_since = 0
        # watchdog transition cursor (utils/health): degradation history
        # rides the session's main record stream, so the dashboard can
        # show WHEN a component stalled, not just its current gauge
        from deeplearning4j_tpu.utils.health import get_health

        self._health = get_health()
        self._health_seq = self._health.last_seq()

    # -- static info (once per session) --------------------------------------

    def _send_static(self, model):
        import jax

        confs = model._ordered_layer_confs()
        layers = [
            {"index": i, "type": type(c).__name__,
             "n_params": int(sum(np.prod(v.shape) for v in p.values()))}
            for i, (c, p) in enumerate(zip(confs, model.params_list))
        ]
        self.router.put_static_info(self.session_id, {
            "model_class": type(model).__name__,
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0].device_kind),
            "n_devices": len(jax.devices()),
            "start_time": time.time(),
            "layers": layers,
            "total_params": int(sum(l["n_params"] for l in layers)),
            "graph": model_graph(model),
        })
        self._sent_static = True

    # -- per iteration --------------------------------------------------------

    def iteration_done(self, model, iteration, info):
        if not self._sent_static:
            self._send_static(model)
        now = time.perf_counter()
        self._samples_since += info.get("batch_size", 0)
        if iteration % self.frequency != 0:
            return
        sps = 0.0
        if self._last_time is not None and now > self._last_time:
            sps = self._samples_since / (now - self._last_time)
        self._last_time = now
        self._samples_since = 0

        rec = {
            "iteration": int(iteration),
            "ts": time.time(),
            "epoch": int(model.epoch),
            "score": float(np.asarray(info["score"]())),
            "etl_ms": float(info.get("etl_ms", 0.0)),
            "samples_per_sec": float(sps),
            "worker": 0,
        }
        stats = info.get("stats", lambda: None)()
        if stats is not None:
            for group in ("grad_mm", "update_mm", "param_mm"):
                per_layer = {}
                for li, layer in enumerate(stats[group]):
                    for pname, v in layer.items():
                        per_layer[f"{li}_{pname}"] = float(np.asarray(v))
                rec[group] = per_layer
        if self.report_memory:
            mem = _device_memory_stats()
            if mem:
                rec["memory"] = mem
        new_tr = self._health.transitions_since(self._health_seq)
        if new_tr:
            from deeplearning4j_tpu.utils.health import LEVELS

            self._health_seq = max(t["seq"] for t in new_tr)
            rec["health_transitions"] = new_tr
            rec["health_level"] = {t["component"]: LEVELS[t["to"]]
                                   for t in new_tr}
        self._reports += 1
        if (self.histogram_bins > 0
                and (self._reports - 1) % self.histogram_frequency == 0):
            hists = {}
            for li, p in enumerate(model.params_list):
                for pname, v in p.items():
                    flat = np.asarray(v).reshape(-1)
                    counts, edges = np.histogram(flat,
                                                 bins=self.histogram_bins)
                    hists[f"{li}_{pname}"] = {
                        "edges": [float(e) for e in edges],
                        "counts": [int(c) for c in counts],
                    }
            rec["hists"] = hists
        self.router.put_update(self.session_id, rec)


class ConvolutionalIterationListener(IterationListener):
    """Streams a grid of first-conv-layer activation maps for the first
    example of the current batch (reference: ConvolutionalIterationListener
    + ConvolutionalListenerModule's /activations page). Stored as plain
    nested lists in the stats stream (record key "activations"); the UI
    renders them as canvas heatmaps — no image encoding dependency."""

    def __init__(self, router: StatsStorageRouter, session_id: str,
                 frequency: int = 10, max_channels: int = 12,
                 max_hw: int = 24):
        self.router = router
        self.session_id = session_id
        self.frequency = max(1, int(frequency))
        self.max_channels = int(max_channels)
        self.max_hw = int(max_hw)

    def iteration_done(self, model, iteration, info):
        if iteration % self.frequency != 0:
            return
        ds = info.get("batch", lambda: None)()
        confs = getattr(model, "layer_confs", None)
        if ds is None or confs is None:  # ComputationGraph: not wired yet
            return
        from deeplearning4j_tpu.nn.conf.layers import ConvolutionLayer

        ci = next((i for i, c in enumerate(confs)
                   if isinstance(c, ConvolutionLayer)), None)
        if ci is None:
            return
        x = np.asarray(ds.features)[:1]
        acts, _ = model._forward(model.params_list, model.state_list,
                                 x, training=False, rng=None,
                                 to_layer=ci + 1)
        a = np.asarray(acts)[0]  # [H, W, C]
        if a.ndim != 3:
            return
        # ceil division: the stride must actually cap output at max_hw
        sh = -(-a.shape[0] // self.max_hw)
        sw = -(-a.shape[1] // self.max_hw)
        a = a[::sh, ::sw, : self.max_channels]
        lo, hi = float(a.min()), float(a.max())
        a = (a - lo) / max(hi - lo, 1e-9)
        self.router.put_update(self.session_id, {
            "iteration": int(iteration),
            "ts": time.time(),
            "activations": {
                "layer": int(ci),
                "channels": [a[:, :, c].round(3).tolist()
                             for c in range(a.shape[-1])],
            },
        })
