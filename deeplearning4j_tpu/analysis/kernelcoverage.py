"""Per-conv-instance Pallas kernel-coverage table.

Answers, for every ConvolutionLayer of a network conf, the question the
per-family roofline verdicts (analysis/costmodel) can only answer in
aggregate: WHICH conv instances route to the Pallas conv+BN-stats kernel
(`ops/pallas_conv_bn`), which are DECLINED by the per-instance roofline
(compute-bound — the stats epilogue saves an HBM read worth nothing
there), and which are structurally unsupported. Shapes come from
`shapeflow.propagate_types` — pure config-graph walking, no init, no
trace, no device — so the table is cheap enough for `cli perf` and the
tier-1 kernel-coverage smoke to print on any host.

The decisions are computed in PLANNING mode (`conv_decision(...,
planning=True)`): the table models the routing on the TPU the kernels
target (bf16 by default), regardless of the local backend or interpret
state. The contract the smoke enforces: every instance resolves to
covered or declined-with-verdict — "unsupported" means a conv shape the
kernel family silently misses, which is exactly the gap this PR closed
(53/53 for ResNet-50).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")


def conv_instances(conf, batch: int = 128) -> List[Tuple[str, dict]]:
    """(layer_name, probe_ctx) for every 2D ConvolutionLayer in a graph
    or multilayer conf, in topological order. probe_ctx is exactly the
    keyword context `nn/layers/conv.conv_forward` passes to the "conv2d"
    helper probe (minus dtype, which the caller supplies). Layers whose
    input type cannot be propagated are skipped — they cannot exist in a
    sane conf and the caller's totals would silently lie otherwise."""
    from deeplearning4j_tpu.analysis.shapeflow import propagate_types
    from deeplearning4j_tpu.nn.conf import layers as L

    def ctx_for(layer, it) -> Optional[dict]:
        if it is None or not hasattr(it, "channels"):
            return None
        n_in = int(layer.n_in) if layer.n_in else int(it.channels)
        return dict(
            kernel=tuple(int(k) for k in layer.kernel_size),
            stride=tuple(int(s) for s in layer.stride),
            dilation=tuple(int(d) for d in layer.dilation),
            same=layer.convolution_mode == L.ConvolutionMode.SAME,
            has_bias=bool(layer.has_bias),
            activation=layer.activation or "identity",
            n_in=n_in,
            n_out=int(layer.n_out),
            x_shape=(int(batch), int(it.height), int(it.width), n_in),
            training=True,
        )

    out: List[Tuple[str, dict]] = []
    types = propagate_types(conf)
    if isinstance(types, list):  # MultiLayerConfiguration
        # layer i's INPUT is layer i-1's output (the propagated list is
        # outputs; shift by one, seeding with the conf input type)
        it = conf.input_type
        for i, layer in enumerate(conf.layers):
            pp = conf.preprocessors.get(str(i))
            if pp is not None and it is not None:
                try:
                    it = pp.output_type(it)
                except Exception:
                    it = None
            if type(layer) is L.ConvolutionLayer:
                ctx = ctx_for(layer, it)
                if ctx is not None:
                    out.append((f"layer{i}", ctx))
            it = types[i]
        return out
    for name in conf.topological_order():
        v = conf.vertices.get(name)
        layer = getattr(v, "layer", None)
        if type(layer) is not L.ConvolutionLayer:
            continue
        ins = conf.vertex_inputs.get(name, [])
        ctx = ctx_for(layer, types.get(ins[0]) if ins else None)
        if ctx is not None:
            out.append((name, ctx))
    return out


def coverage_table(conf, batch: int = 128, dtype=None) -> List[dict]:
    """One row per conv instance: the layer name, its shape, and the
    `conv_decision` routing verdict (covered / declined / unsupported
    with reason, family slug and the roofline numbers that decided it).
    dtype defaults to bf16 — the precision the TPU rounds run."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas_conv_bn import conv_decision

    if dtype is None:
        dtype = jnp.bfloat16
    rows = []
    for name, ctx in conv_instances(conf, batch=batch):
        d = conv_decision(dtype=dtype, planning=True, **ctx)
        row = {
            "layer": name,
            "kernel": list(ctx["kernel"]),
            "stride": list(ctx["stride"]),
            "x_shape": list(ctx["x_shape"]),
            "n_out": ctx["n_out"],
            "status": d["status"],
            "reason": d["reason"],
            "family": d["family"],
        }
        if d["roofline"] is not None:
            row["intensity"] = d["roofline"]["intensity"]
            row["ridge"] = d["roofline"]["ridge_intensity"]
        rows.append(row)
    return rows


def coverage_summary(rows: List[dict]) -> Dict[str, int]:
    counts = {"total": len(rows), "covered": 0, "declined": 0,
              "unsupported": 0}
    for r in rows:
        counts[r["status"]] = counts.get(r["status"], 0) + 1
    return counts


def format_table(rows: List[dict]) -> str:
    s = coverage_summary(rows)
    lines = [f"Pallas conv kernel coverage: {s['total']} conv instances — "
             f"{s['covered']} covered, {s['declined']} declined "
             f"(roofline), {s['unsupported']} unsupported"]
    lines.append(f"  {'layer':<14} {'kernel':>6} {'stride':>6} "
                 f"{'input (NHWC)':>20} {'n_out':>5} {'FLOP/B':>8}  "
                 f"decision")
    for r in rows:
        k = "x".join(str(v) for v in r["kernel"])
        st = "x".join(str(v) for v in r["stride"])
        shape = "x".join(str(v) for v in r["x_shape"])
        inten = f"{r['intensity']:.0f}" if "intensity" in r else "-"
        verdict = r["status"]
        if r["status"] != "covered":
            verdict += f" ({r['reason']})"
        lines.append(f"  {r['layer']:<14} {k:>6} {st:>6} {shape:>20} "
                     f"{r['n_out']:>5} {inten:>8}  {verdict}")
    return "\n".join(lines)


def main(argv=None) -> int:
    """Kernel-coverage smoke (scripts/t1.sh `T1 KERNEL COVERAGE:`):
    assert every conv instance of the preset resolves to covered or
    declined-with-verdict — a silently-unsupported shape fails the
    gate, because that is a kernel-family hole nobody decided on."""
    import argparse

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--preset", default="resnet50", choices=["resnet50"])
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--table", action="store_true",
                   help="print the full per-instance table")
    args = p.parse_args(argv)
    # operator surface: announce through the package logger (library
    # code never prints — lint CC006), same as the server mains
    from deeplearning4j_tpu import configure_logging

    if all(isinstance(h, logging.NullHandler) for h in logger.handlers):
        configure_logging()
    from deeplearning4j_tpu.models.resnet import resnet50_conf

    conf = resnet50_conf()
    rows = coverage_table(conf, batch=args.batch)
    if args.table:
        logger.info("%s", format_table(rows))
    s = coverage_summary(rows)
    ok = s["unsupported"] == 0 and s["total"] > 0
    logger.info(
        "kernel coverage %s (batch %d, bf16): %d conv instances — "
        "%d covered, %d declined (roofline), %d unsupported -> %s",
        args.preset, args.batch, s["total"], s["covered"], s["declined"],
        s["unsupported"], "ok" if ok else "FAIL")
    if not ok:
        for r in rows:
            if r["status"] == "unsupported":
                logger.error(
                    "UNSUPPORTED: %s kernel=%s stride=%s reason=%s",
                    r["layer"], r["kernel"], r["stride"], r["reason"])
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
