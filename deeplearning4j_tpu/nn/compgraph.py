"""ComputationGraph — the DAG network.

Analog of the reference's nn/graph/ComputationGraph.java (3,062 LoC).
TPU-first translation of its design decisions:

- reference: topo order computed once (:340,1055), forward = walk topo
  order calling Vertex.doForward (:1291-1292), backward = reverse walk with
  explicit epsilon accumulation at fan-out vertices (:1480-1502).
- here: the same cached topo order drives a *pure function* of
  (params, inputs) built once and jitted; backward is jax.grad of that
  function, so fan-out accumulation is handled by autodiff and the whole
  step (forward + backward + updater) compiles to one XLA program.

Parameters are a list of per-layer-vertex dicts in topological order —
the same flattening convention as MultiLayerNetwork, so params()/
set_params() and the serializer work identically.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common.dtypes import policy_from_name
from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    DataSetIterator,
    ListDataSetIterator,
    MultiDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph import (
    ComputationGraphConfiguration,
    LayerVertex,
)
from deeplearning4j_tpu.nn.layers.registry import (
    LayerContext,
    forward_layer,
    init_layer_params,
    init_layer_state,
)
from deeplearning4j_tpu.nn.multilayer import (
    _OUTPUT_LAYER_TYPES,
    _is_recurrent,
    _preout_of_output_layer,
    _regularizable,
)
from deeplearning4j_tpu.nn.netbase import NetworkBase
from deeplearning4j_tpu.ops.losses import example_presence, masked_example_mean, loss_value
from deeplearning4j_tpu.train.evaluation import Evaluation
from deeplearning4j_tpu.train.updaters import (
    normalize_gradients,
    schedule_lr,
    updater_from_conf,
)


# -- scan-over-identical-blocks ----------------------------------------------
#
# Deep nets built from repeated identical units (ResNet stage blocks) pay
# trace+compile cost proportional to depth: every unit is re-traced even
# though its program is the same. Detecting maximal runs of
# identically-configured, chain-connected units and compiling each run as
# ONE `lax.scan` over stacked per-unit params collapses that cost to one
# unit body per run — `compile_total{kind="graph_block"}` records k body
# traces unrolled vs 1 scanned. Opt-in via set_block_scan / DL4J_BLOCK_SCAN
# (forward numerics are unchanged; see the block-scan tests in
# tests/test_compgraph.py).

def _vertex_signature(v):
    """Structural identity of a vertex conf: (type, canonical-JSON config),
    or None when the vertex cannot participate in a scanned run."""
    from deeplearning4j_tpu.nn.conf.graph import (
        ElementWiseVertex,
        MergeVertex,
        ScaleVertex,
        ShiftVertex,
    )
    from deeplearning4j_tpu.nn.conf.serde import config_to_dict
    import json as _json

    if isinstance(v, LayerVertex):
        lc = v.layer
        if v.preprocessor is not None:
            return None
        if _is_recurrent(lc) or isinstance(lc, _OUTPUT_LAYER_TYPES):
            return None
        body = lc
    elif isinstance(v, (ElementWiseVertex, MergeVertex, ScaleVertex,
                        ShiftVertex)):
        body = v
    else:
        return None
    try:
        return (type(v).__name__,
                _json.dumps(config_to_dict(body), sort_keys=True))
    except Exception:
        return None


def _detect_block_runs(conf, topo, pidx_map):
    """Find maximal runs of >=2 consecutive identical units in the topo
    order. A unit of period p starting at topo index s repeats at s+p,
    s+2p, ... when each repeated vertex has the same signature and the
    same *relative* input offsets, every offset d at local position q is
    internal (d <= q) or the previous unit's exit (d == q+1), no vertex
    but the run's exit is consumed outside the run, and each unit holds
    at least one layer. Returns run records consumed by _exec_block_run."""
    index = {n: i for i, n in enumerate(topo)}
    n = len(topo)
    sigs = [None] * n
    offsets = [None] * n
    for i, name in enumerate(topo):
        v = conf.vertices.get(name)
        if v is None:  # a graph input
            continue
        sigs[i] = _vertex_signature(v)
        offsets[i] = tuple(i - index[src] for src in conf.vertex_inputs[name])

    consumers = {}
    for name, ins in conf.vertex_inputs.items():
        for src in ins:
            consumers.setdefault(src, []).append(name)

    def unit_ok(s, p):
        """Template unit [s, s+p): signable, chain-connected."""
        for q in range(p):
            i = s + q
            if sigs[i] is None or offsets[i] is None:
                return False
            for d in offsets[i]:
                if not (1 <= d <= q + 1):
                    return False
        return any(
            isinstance(conf.vertices[topo[s + q]], LayerVertex)
            for q in range(p)
        )

    def repeats(s, p):
        k = 1
        while s + (k + 1) * p <= n:
            base = s + k * p
            if all(
                sigs[base + q] == sigs[s + q]
                and offsets[base + q] == offsets[s + q]
                for q in range(p)
            ):
                k += 1
            else:
                break
        return k

    def run_ok(s, p, k):
        lo, hi = s, s + p * k
        exit_name = topo[hi - 1]
        for i in range(lo, hi - 1):
            name = topo[i]
            if name in conf.outputs:
                return False
            for c in consumers.get(name, ()):
                if not (lo <= index[c] < hi):
                    return False
        return exit_name is not None

    runs = []
    i = len(conf.inputs)
    while i < n:
        found = None
        for p in range(1, (n - i) // 2 + 1):
            if not unit_ok(i, p):
                continue
            k = repeats(i, p)
            if k >= 2 and run_ok(i, p, k):
                found = (p, k)
                break  # smallest period = most units collapsed
        if found is None:
            i += 1
            continue
        p, k = found
        unit_names = topo[i:i + p]
        layer_slots = [
            q for q in range(p)
            if isinstance(conf.vertices[unit_names[q]], LayerVertex)
        ]
        pidx_rows = [
            [pidx_map[topo[i + u * p + q]] for q in layer_slots]
            for u in range(k)
        ]
        runs.append({
            "start": i,
            "period": p,
            "count": k,
            "entry": topo[i - 1],
            "exit": topo[i + p * k - 1],
            "unit_names": unit_names,
            "offsets": [offsets[i + q] for q in range(p)],
            "layer_slots": layer_slots,
            "pidx_rows": pidx_rows,
        })
        i += p * k
    return runs


def _as_multidataset(ds) -> MultiDataSet:
    if isinstance(ds, MultiDataSet):
        return ds
    if isinstance(ds, DataSet):
        out = MultiDataSet(
            [ds.features], [ds.labels],
            None if ds.features_mask is None else [ds.features_mask],
            None if ds.labels_mask is None else [ds.labels_mask],
        )
        # keep the wrapper's real-example count for listener accounting
        if hasattr(ds, "reported_examples"):
            out.reported_examples = ds.reported_examples
        return out
    raise TypeError(f"expected DataSet or MultiDataSet, got {type(ds)}")


class ComputationGraph(NetworkBase):
    """DAG network. API mirrors the reference: init, fit, output, score,
    evaluate, params/set_params."""

    def __init__(self, conf: ComputationGraphConfiguration):
        super().__init__()
        self.conf = conf
        self.net_conf = conf.net_conf
        self.policy = policy_from_name(self.net_conf.precision)
        self.updater_def = updater_from_conf(self.net_conf)
        self.topo: List[str] = conf.topological_order()
        self.layer_vertex_names: List[str] = [
            n for n in self.topo if isinstance(conf.vertices.get(n), LayerVertex)
        ]
        self._pidx: Dict[str, int] = {
            n: i for i, n in enumerate(self.layer_vertex_names)
        }
        self._layer_confs: List[L.LayerConf] = [
            conf.vertices[n].layer for n in self.layer_vertex_names
        ]
        self._train_step_fn = None
        self._output_fn = None
        self._block_scan = None  # None = DL4J_BLOCK_SCAN env decides
        self._block_runs_cache = None

    def _ordered_layer_confs(self):
        return self._layer_confs

    # -- scan-over-identical-blocks ------------------------------------------

    def set_block_scan(self, mode=True) -> "ComputationGraph":
        """Compile runs of identically-configured residual blocks as ONE
        scanned body with stacked params instead of tracing every block
        (True/"scan" on, False/"unroll" off, None = DL4J_BLOCK_SCAN env).
        Collapses `compile_total{kind="graph_block"}` and trace time on
        deep nets (ResNet-50 stage blocks); forward numerics unchanged.
        Note: feed_forward() then reports only each run's exit activation
        — per-block intermediates live inside the scan."""
        if mode not in (True, False, None, "scan", "unroll"):
            raise ValueError(
                f"set_block_scan: expected True/'scan', False/'unroll' or "
                f"None, got {mode!r}")
        self._block_scan = mode
        self._block_runs_cache = None
        self._reset_step_programs()
        return self

    def _block_scan_enabled(self) -> bool:
        mode = self._block_scan
        if mode is None:
            import os as _os

            mode = _os.environ.get("DL4J_BLOCK_SCAN", "0")
        return mode in (True, "1", "scan", "on")

    def _block_runs(self):
        """Detected identical-unit runs (cached; detection is pure conf
        analysis, so it is computed even with the scan off — the unrolled
        path uses it to count `graph_block` body traces honestly)."""
        if self._block_runs_cache is None:
            self._block_runs_cache = _detect_block_runs(
                self.conf, self.topo, self._pidx)
        return self._block_runs_cache

    # -- init ----------------------------------------------------------------

    def init(self) -> "ComputationGraph":
        key = jax.random.PRNGKey(self.net_conf.seed)
        dtype = self.policy.param_dtype
        self.params_list = []
        self.state_list = []
        for i, lc in enumerate(self._layer_confs):
            self.params_list.append(
                init_layer_params(jax.random.fold_in(key, i), lc, dtype)
            )
            self.state_list.append(init_layer_state(lc, dtype))
        self.upd_state = self.updater_def.init_tree(self.params_list)
        return self

    # -- forward -------------------------------------------------------------

    def _forward(self, params, states, inputs: Sequence, *, training, rng,
                 input_masks: Optional[Sequence] = None, preout_outputs=False,
                 stateful=False):
        """Pure forward over the cached topo order. Returns
        (activations dict, new_states list). With preout_outputs, loss-head
        vertices also record their post-dropout input features under
        "<name>__features" (the center-loss term needs them). stateful
        seeds empty RNN state so recurrent layers return their carry
        (rnnTimeStep / TBPTT, reference: ComputationGraph rnn methods)."""
        conf = self.conf
        acts: Dict[str, jnp.ndarray] = dict(zip(conf.inputs, inputs))
        masks: Dict[str, jnp.ndarray] = {}
        if input_masks is not None:
            masks = {
                n: m for n, m in zip(conf.inputs, input_masks) if m is not None
            }
        # single-mask convenience: an rnn layer deeper in the graph uses the
        # sole input mask (the multi-input per-branch case needs explicit
        # LastTimeStep/mask vertices, as in the reference)
        sole_mask = next(iter(masks.values())) if len(masks) == 1 else None
        new_states: List[Optional[dict]] = [None] * len(self.layer_vertex_names)
        env = {"activations": acts, "input_masks": masks}
        scan_on = self._block_scan_enabled()
        run_by_start = {r["start"]: r for r in self._block_runs()}
        topo = self.topo
        pos = 0
        while pos < len(topo):
            name = topo[pos]
            if name in acts:
                pos += 1
                continue
            r = run_by_start.get(pos)
            if r is not None:
                x_entry = acts[r["entry"]]
                tracing = isinstance(x_entry, jax.core.Tracer)
                out = None
                if scan_on and self._run_shapes_ok(r, params, states):
                    out = self._exec_block_run(
                        r, params, states, x_entry,
                        training=training, rng=rng, sole_mask=sole_mask)
                if out is not None:
                    exit_act, st_updates = out
                    acts[r["exit"]] = exit_act
                    for pidx, ns in st_updates.items():
                        new_states[pidx] = ns
                    if tracing:
                        self._note_compile("graph_block", r["exit"])
                    pos = r["start"] + r["period"] * r["count"]
                    continue
                if tracing:
                    # unrolled: every unit's body is traced separately —
                    # count each so compile_total{kind="graph_block"}
                    # shows the collapse when the scan is on
                    for _ in range(r["count"]):
                        self._note_compile("graph_block", r["exit"])
            v = conf.vertices[name]
            xs = [acts[i] for i in conf.vertex_inputs[name]]
            if isinstance(v, LayerVertex):
                x = xs[0]
                timesteps = x.shape[1] if x.ndim == 3 else None
                if v.preprocessor is not None:
                    x = v.preprocessor(x, {"timesteps": timesteps})
                    if hasattr(x, "ndim") and x.ndim == 3:
                        timesteps = x.shape[1]
                pidx = self._pidx[name]
                lc = v.layer
                st = states[pidx]
                if stateful and _is_recurrent(lc) and st is None:
                    st = {}  # empty dict triggers zero-state seed + carry
                ctx = LayerContext(
                    training=training,
                    rng=jax.random.fold_in(rng, pidx) if rng is not None else None,
                    mask=sole_mask if (hasattr(x, "ndim") and x.ndim == 3) else None,
                    timesteps=timesteps,
                    state=st,
                )
                if (
                    preout_outputs
                    and name in conf.outputs
                    and isinstance(lc, _OUTPUT_LAYER_TYPES)
                ):
                    from deeplearning4j_tpu.nn.layers.core import apply_dropout

                    x = apply_dropout(x, lc.dropout, ctx)
                    acts[name + "__features"] = x
                    x = _preout_of_output_layer(lc, params[pidx], x)
                    ns = None
                else:
                    x, ns = forward_layer(lc, params[pidx], x, ctx)
                new_states[pidx] = ns
                acts[name] = x
            else:
                acts[name] = v.forward(xs, env)
            pos += 1
        return acts, new_states

    def _run_shapes_ok(self, r, params, states) -> bool:
        """True when every unit's params/state trees share structure and
        leaf shapes — the precondition for stacking them (cached on the
        run record; shapes are fixed after init)."""
        cached = r.get("_shapes_ok")
        if cached is not None:
            return cached

        def sig(tree):
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            return (str(treedef),
                    tuple((tuple(l.shape), str(l.dtype)) for l in leaves))

        ok = True
        rows = r["pidx_rows"]
        for j in range(len(r["layer_slots"])):
            base = (sig(params[rows[0][j]]), sig(states[rows[0][j]]))
            for row in rows[1:]:
                if (sig(params[row[j]]), sig(states[row[j]])) != base:
                    ok = False
        r["_shapes_ok"] = ok
        return ok

    def _exec_block_run(self, r, params, states, x, *, training, rng,
                        sole_mask):
        """Run one detected identical-unit run as a single `lax.scan`:
        per-unit params/states stacked in-graph (leading unit axis), the
        unit body replicating the per-vertex walk with run-local
        activations, the entry activation as carry. Per-layer rng keys
        fold in the REAL pidx (fed as scan xs), so dropout draws match
        the unrolled walk. Returns (exit activation, {pidx: new_state})
        or None when the unit is not shape-invariant (strided/shrinking
        units cannot be a scan carry) — caller falls back to unrolling."""
        conf = self.conf
        p, k = r["period"], r["count"]
        slots = r["layer_slots"]
        rows = r["pidx_rows"]
        unit_names = r["unit_names"]
        offsets = r["offsets"]
        slot_of = {q: j for j, q in enumerate(slots)}

        stack = lambda trees: jax.tree_util.tree_map(
            lambda *a: jnp.stack(a), *trees)
        sp = tuple(stack([params[row[j]] for row in rows])
                   for j in range(len(slots)))
        ss = tuple(stack([states[row[j]] for row in rows])
                   for j in range(len(slots)))
        pmat = jnp.asarray(rows, jnp.int32)  # [k, n_slots]

        def run_unit(carry, up, us, prow):
            local: Dict[int, jnp.ndarray] = {}
            new_sts = []
            for q, vname in enumerate(unit_names):
                v = conf.vertices[vname]
                srcs = [carry if d == q + 1 else local[q - d]
                        for d in offsets[q]]
                if isinstance(v, LayerVertex):
                    xq = srcs[0]
                    j = slot_of[q]
                    ctx = LayerContext(
                        training=training,
                        rng=(jax.random.fold_in(rng, prow[j])
                             if rng is not None else None),
                        mask=sole_mask if xq.ndim == 3 else None,
                        timesteps=xq.shape[1] if xq.ndim == 3 else None,
                        state=us[j],
                    )
                    y, ns = forward_layer(v.layer, up[j], xq, ctx)
                    new_sts.append(ns)
                else:
                    y = v.forward(srcs, {})
                local[q] = y
            return local[p - 1], tuple(new_sts)

        # scan-carry contract: one abstract unit application must preserve
        # the entry activation's shape/dtype (a strided unit would not)
        try:
            probe = jax.eval_shape(
                lambda a: run_unit(
                    a,
                    tuple(params[i] for i in rows[0]),
                    tuple(states[i] for i in rows[0]),
                    pmat[0],
                )[0],
                x,
            )
        except Exception:
            return None
        if probe.shape != x.shape or probe.dtype != x.dtype:
            return None

        def body(carry, xs_scan):
            up, us, prow = xs_scan
            return run_unit(carry, up, us, prow)

        exit_act, ys = jax.lax.scan(body, x, (sp, ss, pmat))
        updates = {}
        for j in range(len(slots)):
            nsj = ys[j]
            if nsj is None:
                continue
            for u in range(k):
                updates[rows[u][j]] = jax.tree_util.tree_map(
                    lambda a, u=u: a[u], nsj)
        return exit_act, updates

    def _merge_states(self, old, new):
        return [n if n is not None else o for o, n in zip(old, new)]

    # -- loss ----------------------------------------------------------------

    def _loss(self, params, states, xs, ys, f_masks, l_masks, rng, training=True):
        conf = self.conf
        xs = [self.policy.cast_input(x) for x in xs]
        acts, new_states = self._forward(
            params, states, xs, training=training, rng=rng,
            input_masks=f_masks, preout_outputs=True,
        )
        score = 0.0
        n_heads = 0
        for i, name in enumerate(conf.outputs):
            v = conf.vertices[name]
            if not (isinstance(v, LayerVertex)
                    and isinstance(v.layer, _OUTPUT_LAYER_TYPES)):
                continue
            lc = v.layer
            lm = l_masks[i] if l_masks is not None else None
            per_ex = loss_value(
                lc.loss, ys[i], self.policy.cast_output(acts[name]),
                lc.activation, lm,
            )
            score = score + masked_example_mean(per_ex, lm)
            if isinstance(lc, L.CenterLossOutputLayer):
                # center loss head (reference: CenterLossOutputLayer.java):
                # + lambda * mean(0.5||f - c_y||^2) on the head's input
                # features, centers EMA-updated as non-trainable state
                pidx = self._pidx[name]
                feats = acts[name + "__features"]
                centers = states[pidx]["centers"].astype(feats.dtype)
                y32 = ys[i].astype(feats.dtype)
                diff = feats - y32 @ centers
                center_per_ex = 0.5 * jnp.sum(diff * diff, axis=-1)
                present = example_presence(per_ex, lm)
                score = score + lc.lambda_ * (
                    jnp.sum(center_per_ex * present)
                    / jnp.maximum(jnp.sum(present), 1.0))
                if training:
                    f_sg = jax.lax.stop_gradient(feats)
                    yw = y32 * present[:, None]
                    counts = jnp.sum(yw, axis=0)[:, None]
                    means = (yw.T @ f_sg) / jnp.maximum(counts, 1.0)
                    updated = jnp.where(
                        counts > 0,
                        (1.0 - lc.alpha) * centers + lc.alpha * means,
                        centers,
                    )
                    new_states[pidx] = {
                        "centers": updated.astype(states[pidx]["centers"].dtype)
                    }
            n_heads += 1
        if n_heads == 0:
            raise ValueError(
                "no output vertex is a loss head (OutputLayer/RnnOutputLayer/"
                "LossLayer) — cannot compute a training loss"
            )
        reg = 0.0
        for lc, p in zip(self._layer_confs, params):
            inner = lc.inner if isinstance(lc, L.FrozenLayer) else lc
            l1 = getattr(inner, "l1", 0.0) or 0.0
            l2 = getattr(inner, "l2", 0.0) or 0.0
            if l1 == 0.0 and l2 == 0.0:
                continue
            for pname, w in p.items():
                if _regularizable(pname):
                    if l1:
                        reg = reg + l1 * jnp.sum(jnp.abs(w))
                    if l2:
                        reg = reg + 0.5 * l2 * jnp.sum(w * w)
        return score + reg, new_states

    # -- train step ----------------------------------------------------------

    def _lr_mult_tree(self):
        base = self.net_conf.learning_rate
        out = []
        for lc, p in zip(self._layer_confs, self.params_list):
            inner = lc.inner if isinstance(lc, L.FrozenLayer) else lc
            layer_lr = getattr(inner, "learning_rate", None)
            bias_lr = getattr(inner, "bias_learning_rate", None)
            mult = {}
            for name in p:
                if name == "b" and bias_lr is not None:
                    mult[name] = bias_lr / base
                elif layer_lr is not None:
                    mult[name] = layer_lr / base
                else:
                    mult[name] = 1.0
            out.append(mult)
        return out

    def _trainable_mask(self):
        return [
            {k: (0.0 if isinstance(lc, L.FrozenLayer) else 1.0) for k in p}
            for lc, p in zip(self._layer_confs, self.params_list)
        ]

    @staticmethod
    def _jas(lst):
        """Optional list-of-optional-arrays -> device arrays (mask lists
        may be None wholesale or per-entry)."""
        if lst is None:
            return None
        return [None if a is None else jnp.asarray(a) for a in lst]

    def _seeded_states(self):
        """state_list copy with {} seeded for recurrent layers (the
        TBPTT zero-state trigger, shared by the loop and fused paths)."""
        states = list(self.state_list)
        for i, lc in enumerate(self._layer_confs):
            if _is_recurrent(lc) and states[i] is None:
                states[i] = {}
        return states

    def _std_loss_builder(self):
        def loss_builder(p, states, data, rng):
            xs, ys, fms, lms = data
            return self._loss(p, states, xs, ys, fms, lms, rng)

        return loss_builder

    def _trunc_loss_builder(self):
        """TBPTT loss with tbptt_bwd_length < tbptt_fwd_length: slice A
        advances state under stop_gradient (score counts, no gradient),
        slice B backprops — same design as MultiLayerNetwork's
        _trunc_loss_builder, generalized to multi-input/multi-output."""

        def loss_builder(p, states, data, rng):
            xsA, ysA, fmsA, lmsA, xsB, ysB, fmsB, lmsB = data
            lossA, statesA = self._loss(p, states, xsA, ysA, fmsA, lmsA,
                                        rng)
            carried = self._merge_states(states, statesA)
            carried = jax.tree_util.tree_map(jax.lax.stop_gradient, carried)
            lossB, statesB = self._loss(
                p, carried, xsB, ysB, fmsB, lmsB,
                None if rng is None else jax.random.fold_in(rng, 1),
            )
            nA = max(x.shape[1] for x in xsA if x.ndim == 3)
            nB = max(x.shape[1] for x in xsB if x.ndim == 3)
            score = (
                jax.lax.stop_gradient(lossA) * nA + lossB * nB
            ) / (nA + nB)
            return score, self._merge_states(carried, statesB)

        return loss_builder

    def _make_step_body(self, loss_builder=None, collect: bool = False):
        """Unjitted optimizer-step body around a loss builder
        (p, states, data, rng) -> (score, new_states) — same tail as
        MultiLayerNetwork's: gradient masking/normalization, per-leaf lr,
        updater, param update, plus the in-graph `[loss, grad_norm]`
        divergence diagnostic returned next to the score (see the MLN
        docstring). Shared by the single-step, truncated, fused-TBPTT
        and multi-batch programs."""
        if loss_builder is None:
            loss_builder = self._std_loss_builder()
        gnorm = self.net_conf.gradient_normalization
        gthresh = self.net_conf.gradient_normalization_threshold
        mults = self._lr_mult_tree()
        tmask = self._trainable_mask()
        updater = self.updater_def
        minimize = self.net_conf.minimize
        # in-graph bucketed gradient all-reduce under a mesh plan — same
        # emission as MultiLayerNetwork._make_step_body (see the comment
        # there; the schedule lives in parallel/sharded.CollectivePlan)
        plan = self._mesh_plan

        def step(params, states, upd_state, data, lr, t, rng):
            def loss_fn(p):
                return loss_builder(p, states, data, rng)

            (score, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            if plan is not None:
                grads = plan.reduce_grads(self, grads)
            # global grad norm of the RAW gradient (before masking/
            # clipping), accumulated in f32 — the sentinel diagnostic
            gsq = jnp.float32(0.0)
            for g in jax.tree_util.tree_leaves(grads):
                gsq = gsq + jnp.sum(jnp.square(g.astype(jnp.float32)))
            diag = jnp.stack([score.astype(jnp.float32), jnp.sqrt(gsq)])
            if not minimize:
                grads = jax.tree_util.tree_map(lambda g: -g, grads)
            grads = [
                {k: g[k] * m[k] for k in g} for g, m in zip(grads, tmask)
            ]
            grads = normalize_gradients(grads, gnorm, gthresh)
            lr_tree = [
                {k: lr * m[k] for k in g} for g, m in zip(grads, mults)
            ]
            updates, new_upd = updater.apply_tree(grads, upd_state, lr_tree, t)
            new_params = jax.tree_util.tree_map(jnp.add, params, updates)
            merged = self._merge_states(states, new_states)
            if collect:
                # per-layer mean |x| scalars for the stats pipeline
                # (reference: BaseStatsListener mean magnitudes)
                mm = lambda tree: [
                    {k: jnp.mean(jnp.abs(v)) for k, v in p.items()}
                    for p in tree
                ]
                stats = {"grad_mm": mm(grads), "update_mm": mm(updates),
                         "param_mm": mm(new_params)}
                return new_params, merged, new_upd, score, diag, stats
            return new_params, merged, new_upd, score, diag

        return step

    def _build_train_step(self):
        body = self._make_step_body(
            collect=bool(getattr(self, "_collect_stats", False)))

        def step(params, states, upd_state, xs, ys, f_masks, l_masks,
                 lr, t, rng):
            return body(params, states, upd_state,
                        (xs, ys, f_masks, l_masks), lr, t, rng)

        return self._jit_step(step, data_argnums=(3, 4, 5, 6))

    def _fit_step(self, xs, ys, f_masks, l_masks, stateful_states=None):
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
            self._note_compile("train_step")
        lr = schedule_lr(self.net_conf, self.iteration)
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.net_conf.seed ^ 0x5EED), self.iteration
        )
        states = stateful_states if stateful_states is not None else self.state_list
        out = self._train_step_fn(
            self.params_list, states, self.upd_state,
            [jnp.asarray(x) for x in xs], [jnp.asarray(y) for y in ys],
            self._jas(f_masks), self._jas(l_masks),
            jnp.asarray(lr, jnp.float32), jnp.asarray(float(self.iteration)),
            rng,
        )
        params, states, upd, score = out[:4]
        self._step_diag = out[4]
        self._last_stats = out[5] if len(out) > 5 else None
        self.params_list = params
        self.upd_state = upd
        self._score = score
        self.iteration += 1
        return states, score

    # -- fit -----------------------------------------------------------------

    def fit(self, data, labels=None, *, epochs: int = 1, batch_size: int = 32,
            async_prefetch: bool = True, prefetch_buffer: int = 4,
            hang_timeout: float = None, resume_from: str = None,
            run_ledger=None):
        """Train. Accepts (features, labels) arrays, a DataSet/MultiDataSet,
        or a DataSetIterator/MultiDataSetIterator (reference:
        ComputationGraph.fit overloads :857-867). With async_prefetch the
        staged input pipeline (nn/netbase._stage_input_pipeline) feeds the
        loop; prefetch_buffer is the host stage's queue depth.
        `hang_timeout` (seconds) arms the hang watchdog: a stalled step
        raises utils.health.StepHangError with a flight-recorder dump
        path instead of blocking forever — pick it above the worst-case
        single phase (first-step trace+compile, longest legitimate data
        wait). `resume_from` names a checkpoint directory: the newest
        checkpoint loads into this net and the iterator fast-forwards to
        the saved mid-epoch position (empty directory = fresh start;
        `epochs` stays the TOTAL target)."""
        self._require_init()
        if isinstance(data, (DataSetIterator, MultiDataSetIterator)):
            iterator = data
        elif isinstance(data, MultiDataSet):
            iterator = _ListMultiIterator(data, batch_size)
        elif isinstance(data, DataSet):
            iterator = ListDataSetIterator(data, batch_size)
        else:
            iterator = ListDataSetIterator(
                DataSet(np.asarray(data), np.asarray(labels)), batch_size
            )
        return self._run_fit(iterator, epochs, async_prefetch,
                             prefetch_buffer, hang_timeout=hang_timeout,
                             resume_from=resume_from,
                             run_ledger=run_ledger)

    def _fit_dataset(self, ds):
        mds = _as_multidataset(ds)
        if (
            self.conf.backprop_type == "tbptt"
            and any(f.ndim == 3 for f in mds.features)
        ):
            self._fit_tbptt(mds)
            return
        states, _ = self._fit_step(
            mds.features, mds.labels, mds.features_masks, mds.labels_masks
        )
        self.state_list = states
        self._notify(getattr(mds, "reported_examples", None)
                     or mds.num_examples(), mds)

    # -- multi-batch fused fit (set_fused_steps) -----------------------------

    def _fused_fit_supported(self) -> bool:
        return True

    def _fit_datasets_fused(self, ds_list):
        """K same-shape minibatches in ONE jitted dispatch (see
        NetworkBase.set_fused_steps). TBPTT graphs run per-batch — each
        batch still fuses ALL its segments into one dispatch via
        _fit_tbptt_fused; only the cross-batch stacking is MLN-only (the
        MLN carries the recurrent benchmarks)."""
        mds_list = [_as_multidataset(d) for d in ds_list]
        if (
            self.conf.backprop_type == "tbptt"
            and any(f.ndim == 3 for f in mds_list[0].features)
        ):
            for mds in mds_list:
                self._fit_tbptt(mds)
            return
        K = len(mds_list)
        cached = getattr(self, "_multi_fit_fn", None)
        if cached is None or cached[0] != K:
            self._multi_fit_fn = (K, self._build_multi_fit_step(K))
        fn = self._multi_fit_fn[1]
        stack_list = lambda lists: [
            jnp.stack([jnp.asarray(a) for a in pos]) for pos in zip(*lists)
        ]
        stack_masks = lambda lists: (
            None if lists[0] is None
            else [None if pos[0] is None
                  else jnp.stack([jnp.asarray(a) for a in pos])
                  for pos in zip(*lists)]
        )
        xs = stack_list([m.features for m in mds_list])
        ys = stack_list([m.labels for m in mds_list])
        fms = stack_masks([m.features_masks for m in mds_list])
        lms = stack_masks([m.labels_masks for m in mds_list])
        lrs = jnp.asarray(
            [schedule_lr(self.net_conf, self.iteration + i)
             for i in range(K)], jnp.float32)
        params, states, upd, last, diag = fn(
            self.params_list, self.state_list, self.upd_state,
            xs, ys, fms, lms, lrs, jnp.asarray(self.iteration, jnp.uint32))
        self.params_list = params
        self.upd_state = upd
        self.state_list = states
        self._score = last
        self._step_diag = diag
        self._last_stats = None
        self.iteration += K

    def _build_multi_fit_step(self, K: int):
        """K optimizer steps as one `lax.scan` over the stacked batches —
        same per-step lr/t/rng derivation as `_fit_step`, K-1 fewer
        dispatches (equivalence: tests/test_fused_fit.py)."""
        assert not getattr(self, "_collect_stats", False)
        body = self._make_step_body(collect=False)
        seed_key_base = self.net_conf.seed ^ 0x5EED

        def step(params, states, upd_state, xs, ys, fms, lms, lrs, t0):
            key = jax.random.PRNGKey(seed_key_base)

            def scan_body(carry, inp):
                p, st, us = carry
                xs_i, ys_i, fms_i, lms_i, lr, i = inp
                rng, t = self._step_rng_and_t(key, t0, i)
                p, st, us, sc, dg = body(p, st, us,
                                         (xs_i, ys_i, fms_i, lms_i),
                                         lr, t, rng)
                return (p, st, us), (sc, dg)

            (params, states, upd_state), (scores, diags) = jax.lax.scan(
                scan_body, (params, states, upd_state),
                (xs, ys, fms, lms, lrs, jnp.arange(K, dtype=jnp.uint32)))
            diag = jnp.stack([diags[-1, 0], jnp.max(diags[:, 1])])
            return params, states, upd_state, scores[-1], diag

        # stacked batches: [K, B, ...] — batch dim 1 shards over "data"
        return self._jit_step(step, data_argnums=(3, 4, 5, 6),
                              stacked_data=True)

    def _fit_tbptt(self, mds: MultiDataSet):
        """Truncated BPTT over a MultiDataSet: the time axis of every 3-d
        feature/label/mask is segmented into tbptt_fwd_length chunks; RNN
        state carries across segment steps (reference:
        ComputationGraph.doTruncatedBPTT — same segment loop as the MLN
        path, generalized to multi-input/multi-output).

        When eligible (no ragged tail, every temporal array shares T, no
        listeners, no stats collection) all segments run in ONE jitted
        dispatch — the same fused treatment as
        MultiLayerNetwork._fit_tbptt_fused; listeners keep the loop path
        so per-iteration callbacks observe their iteration's params."""
        T = max(f.shape[1] for f in mds.features if f.ndim == 3)
        seg = int(self.conf.tbptt_fwd_length)
        bwd = int(self.conf.tbptt_bwd_length)
        n_seg = -(-T // seg)
        uniform_T = all(
            a.shape[1] == T
            for group in (mds.features, mds.labels) for a in group
            if a.ndim == 3
        ) and all(
            m.shape[1] == T
            for group in (mds.features_masks, mds.labels_masks)
            if group is not None for m in group
            if m is not None and m.ndim == 2
        )
        if (
            T == n_seg * seg
            and uniform_T
            and not self.listeners
            and not getattr(self, "_collect_stats", False)
        ):
            self._fit_tbptt_fused(mds, n_seg, seg, bwd)
            return
        states = self._seeded_states()

        def cut_mask(m, sl):
            if m is None:
                return None
            return m if m.ndim == 1 else m[:, sl]  # 1-D = per-example mask

        def cut(sl):
            feats = [f[:, sl] if f.ndim == 3 else f for f in mds.features]
            labels = [y[:, sl] if y.ndim == 3 else y for y in mds.labels]
            fms = None
            if mds.features_masks is not None:
                fms = [cut_mask(m, sl) for m in mds.features_masks]
            lms = None
            if mds.labels_masks is not None:
                lms = [cut_mask(m, sl) for m in mds.labels_masks]
            return (feats, labels, fms, lms)

        for start in range(0, T, seg):
            end = min(start + seg, T)
            if bwd < end - start:
                boundary = end - bwd
                states, _ = self._fit_step_truncated(
                    cut(slice(start, boundary)), cut(slice(boundary, end)),
                    stateful_states=states,
                )
            else:
                states, _ = self._fit_step(
                    *cut(slice(start, end)), stateful_states=states
                )
            self._notify(getattr(mds, "reported_examples", None)
                     or mds.num_examples(), mds)
        # persist only non-RNN state (running stats); RNN carry is per-batch
        self.state_list = [
            st if not _is_recurrent(lc) else self.state_list[i]
            for i, (lc, st) in enumerate(zip(self._layer_confs, states))
        ]

    @staticmethod
    def _make_seg_data_multi(seg: int, bwd: int):
        """Multi-input TBPTT time segmentation under jit (the list analog
        of MultiLayerNetwork._make_seg_data): temporal arrays (3-d
        features/labels, 2-d masks) get dynamic_slice'd, static arrays
        (2-d labels, 1-d per-example masks) pass through whole."""

        def seg_slice(a, start, length):
            return jax.lax.dynamic_slice_in_dim(a, start, length, axis=1)

        def cut_arrays(lst, s0, ln):
            return [seg_slice(a, s0, ln) if a.ndim == 3 else a for a in lst]

        def cut_masks(lst, s0, ln):
            if lst is None:
                return None
            return [
                None if m is None
                else (m if m.ndim == 1 else seg_slice(m, s0, ln))
                for m in lst
            ]

        def seg_data(xs, ys, fms, lms, i):
            start = i * seg
            if bwd < seg:
                nA = seg - bwd
                return (
                    cut_arrays(xs, start, nA), cut_arrays(ys, start, nA),
                    cut_masks(fms, start, nA), cut_masks(lms, start, nA),
                    cut_arrays(xs, start + nA, bwd),
                    cut_arrays(ys, start + nA, bwd),
                    cut_masks(fms, start + nA, bwd),
                    cut_masks(lms, start + nA, bwd),
                )
            return (cut_arrays(xs, start, seg), cut_arrays(ys, start, seg),
                    cut_masks(fms, start, seg), cut_masks(lms, start, seg))

        return seg_data

    def _build_tbptt_fused_step(self, n_seg: int, seg: int, bwd: int):
        """ALL of a batch's TBPTT segments in ONE jitted dispatch — the
        ComputationGraph twin of MultiLayerNetwork._build_tbptt_fused_step
        (same per-segment lr/t/rng, same optimizer tail; equivalence:
        tests/test_fused_fit.py). Callers guarantee T == n_seg * seg and
        that stats collection is off."""
        assert not getattr(self, "_collect_stats", False)
        body = self._make_step_body(
            self._trunc_loss_builder() if bwd < seg
            else self._std_loss_builder()
        )
        seed_key_base = self.net_conf.seed ^ 0x5EED
        seg_data = self._make_seg_data_multi(seg, bwd)

        def step(params, states, upd_state, data, lrs, t0, _rng_unused):
            xs, ys, fms, lms = data
            key = jax.random.PRNGKey(seed_key_base)

            def run_seg(params, states, upd_state, i):
                rng, t = self._step_rng_and_t(key, t0, i)
                return body(params, states, upd_state,
                            seg_data(xs, ys, fms, lms, i), lrs[i], t, rng)

            # segment 0 inline: its merged states establish the carry
            # pytree (zero-state {} -> populated h/c) for the scan
            params, states, upd_state, s0, d0 = run_seg(
                params, states, upd_state, 0)
            if n_seg == 1:
                return params, states, upd_state, s0, d0

            def scan_body(carry, i):
                p, st, us = carry
                p, st, us, score, dg = run_seg(p, st, us, i)
                return (p, st, us), (score, dg)

            (params, states, upd_state), (scores, diags) = jax.lax.scan(
                scan_body, (params, states, upd_state),
                jnp.arange(1, n_seg))
            diag = jnp.stack([diags[-1, 0],
                              jnp.maximum(d0[1], jnp.max(diags[:, 1]))])
            return params, states, upd_state, scores[-1], diag

        return self._jit_step(step)

    def _fit_tbptt_fused(self, mds: MultiDataSet, n_seg: int, seg: int,
                         bwd: int):
        sig = (n_seg, seg, bwd)
        cached = getattr(self, "_fused_tbptt_fn", None)
        if cached is None or cached[0] != sig:
            self._fused_tbptt_fn = (
                sig, self._build_tbptt_fused_step(n_seg, seg, bwd))
        step_fn = self._fused_tbptt_fn[1]
        states = self._seeded_states()
        lrs = jnp.asarray(
            [schedule_lr(self.net_conf, self.iteration + i)
             for i in range(n_seg)], jnp.float32)
        data = ([jnp.asarray(x) for x in mds.features],
                [jnp.asarray(y) for y in mds.labels],
                self._jas(mds.features_masks), self._jas(mds.labels_masks))
        params, states, upd, last, diag = step_fn(
            self.params_list, states, self.upd_state, data, lrs,
            jnp.asarray(self.iteration, jnp.uint32), None)
        self.params_list = params
        self.upd_state = upd
        self._score = last
        self._step_diag = diag
        self._last_stats = None
        self.iteration += n_seg
        # persist only non-RNN state (running stats); RNN carry is per-batch
        self.state_list = [
            st if not _is_recurrent(lc) else self.state_list[i]
            for i, (lc, st) in enumerate(zip(self._layer_confs, states))
        ]

    def _fit_step_truncated(self, dataA, dataB, stateful_states):
        """TBPTT segment step with a backward-truncation boundary (the
        truncated loss builder above) — one jitted call per segment on
        the loop path."""
        if getattr(self, "_trunc_step_fn", None) is None:
            body = self._make_step_body(
                self._trunc_loss_builder(),
                collect=bool(getattr(self, "_collect_stats", False)))
            self._trunc_step_fn = self._jit_step(body)
            self._note_compile("train_step_truncated")

        lr = schedule_lr(self.net_conf, self.iteration)
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.net_conf.seed ^ 0x5EED), self.iteration
        )
        pack = lambda d: (
            [jnp.asarray(x) for x in d[0]], [jnp.asarray(y) for y in d[1]],
            self._jas(d[2]), self._jas(d[3]),
        )
        out = self._trunc_step_fn(
            self.params_list, stateful_states, self.upd_state,
            pack(dataA) + pack(dataB),
            jnp.asarray(lr, jnp.float32), jnp.asarray(float(self.iteration)),
            rng,
        )
        params, states, upd, score = out[:4]
        self._step_diag = out[4]
        self._last_stats = out[5] if len(out) > 5 else None
        self.params_list = params
        self.upd_state = upd
        self._score = score
        self.iteration += 1
        return states, score

    # -- inference -----------------------------------------------------------

    def output(self, *inputs, input_masks: Optional[Sequence] = None):
        """Forward pass; returns one array for a single-output graph, else
        a list in set_outputs order (reference: ComputationGraph.output,
        incl. the output(INDArray[], masks) overloads — input_masks aligns
        with the graph's inputs and feeds mask-aware vertices such as
        LastTimeStepVertex)."""
        self._require_init()
        xs = [jnp.asarray(x) for x in inputs]
        masks = None
        if input_masks is not None:
            if len(input_masks) != len(self.conf.inputs):
                raise ValueError(
                    f"input_masks has {len(input_masks)} entries but the "
                    f"graph has {len(self.conf.inputs)} inputs "
                    f"({self.conf.inputs}); pass one mask (or None) per input"
                )
            masks = [
                None if m is None else jnp.asarray(m) for m in input_masks
            ]
        # shape-keyed jit cache + compile counter (same contract as
        # MultiLayerNetwork.output — see output_compile_count)
        key = (
            tuple((x.shape, str(x.dtype)) for x in xs),
            None if masks is None else tuple(
                None if m is None else (m.shape, str(m.dtype)) for m in masks
            ),
        )
        def make_fn():
            def fwd(params, states, xs, masks):
                xs = [self.policy.cast_input(x) for x in xs]
                acts, _ = self._forward(
                    params, states, xs, training=False, rng=None,
                    input_masks=masks,
                )
                return [
                    self.policy.cast_output(acts[n])
                    for n in self.conf.outputs
                ]

            return jax.jit(fwd)

        fn = self._cached_output_fn(key, make_fn)
        outs = fn(self.params_list, self.state_list, xs, masks)
        return outs[0] if len(outs) == 1 else outs

    def feed_forward(self, *inputs):
        """All vertex activations as a dict — debugging/inspection path."""
        self._require_init()
        acts, _ = self._forward(
            self.params_list, self.state_list,
            [jnp.asarray(x) for x in inputs], training=False, rng=None,
        )
        return acts

    def score(self, data, labels=None) -> float:
        self._require_init()
        if isinstance(data, (DataSet, MultiDataSet)):
            mds = _as_multidataset(data)
        else:
            mds = _as_multidataset(DataSet(np.asarray(data), np.asarray(labels)))
        s, _ = self._loss(
            self.params_list, self.state_list,
            [jnp.asarray(x) for x in mds.features],
            [jnp.asarray(y) for y in mds.labels],
            None if mds.features_masks is None else [
                None if m is None else jnp.asarray(m) for m in mds.features_masks
            ],
            None if mds.labels_masks is None else [
                None if m is None else jnp.asarray(m) for m in mds.labels_masks
            ],
            rng=None, training=False,
        )
        return float(s)

    def evaluate(self, data, labels=None, batch_size: int = 256,
                 output_index: int = 0) -> Evaluation:
        """Classification evaluation; multi-input graphs evaluate on all
        features, multi-output graphs on the head selected by
        output_index (reference: ComputationGraph.evaluate)."""
        ev = Evaluation()
        if isinstance(data, (DataSetIterator, MultiDataSetIterator)):
            batches = data
        elif isinstance(data, (DataSet, MultiDataSet)):
            batches = [data]
        else:
            batches = DataSet(np.asarray(data), np.asarray(labels)).split_batches(batch_size)
        for b in batches:
            mds = _as_multidataset(b)
            out = self.output(*mds.features, input_masks=mds.features_masks)
            if isinstance(out, list):
                out = out[output_index]
            lm = (
                None if mds.labels_masks is None
                else mds.labels_masks[output_index]
            )
            ev.eval_batch(mds.labels[output_index], out, lm)
        return ev

    # -- rnn streaming inference ---------------------------------------------

    def rnn_time_step(self, *inputs):
        """Stateful streaming inference over the graph (reference:
        ComputationGraph.rnnTimeStep). Each input: [batch, time, nIn] (or
        [batch, nIn] for a single step). Returns outputs in set_outputs
        order (single array for a single-output graph)."""
        self._require_init()
        xs = [jnp.asarray(x) for x in inputs]
        single = all(x.ndim == 2 for x in xs)
        if single:
            xs = [x[:, None, :] for x in xs]
        # only the recurrent carry is held between calls; non-recurrent
        # state (BN running stats) is always read fresh from state_list so
        # streaming matches output() even after an interleaved fit()
        carry = getattr(self, "_rnn_carry", None) or {}
        # a batch-size change is a NEW stream: drop the stale carry
        # (same contract as MultiLayerNetwork.rnn_time_step) instead of
        # leaking a previous caller's hidden state into this one
        bsz = xs[0].shape[0]
        if carry and any(v.shape[0] != bsz
                         for st in carry.values() for v in st.values()):
            carry = {}
            self._rnn_carry = None
        states = [
            carry.get(i, {}) if _is_recurrent(lc) else self.state_list[i]
            for i, lc in enumerate(self._layer_confs)
        ]
        acts, new_states = self._forward(
            self.params_list, states,
            [self.policy.cast_input(x) for x in xs],
            training=False, rng=None, stateful=True,
        )
        merged = self._merge_states(states, new_states)
        self._rnn_carry = {
            i: merged[i]
            for i, lc in enumerate(self._layer_confs) if _is_recurrent(lc)
        }
        outs = [self.policy.cast_output(acts[n]) for n in self.conf.outputs]
        if single:
            outs = [o[:, 0] if o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def rnn_clear_previous_state(self):
        self._rnn_carry = None

    def clear_rnn_state(self):
        """Alias of rnn_clear_previous_state (the MultiLayerNetwork
        streaming API carries the same name)."""
        self.rnn_clear_previous_state()

    def clone(self) -> "ComputationGraph":
        import copy

        other = ComputationGraph(copy.deepcopy(self.conf))
        if self.params_list is not None:
            other.init()
            other.params_list = jax.tree_util.tree_map(
                lambda a: a, self.params_list
            )
            other.state_list = [
                None if s is None else dict(s) for s in self.state_list
            ]
            other.upd_state = jax.tree_util.tree_map(lambda a: a, self.upd_state)
            other.iteration = self.iteration
            other.epoch = self.epoch
        return other


class _ListMultiIterator(MultiDataSetIterator):
    """Minibatches from one in-memory MultiDataSet."""

    def __init__(self, mds: MultiDataSet, batch: int):
        self.mds = mds
        self.batch = batch

    def __iter__(self):
        n = self.mds.num_examples()
        for i in range(0, n, self.batch):
            sl = slice(i, min(i + self.batch, n))

            def cut(arrs):
                return None if arrs is None else [
                    None if a is None else a[sl] for a in arrs
                ]

            yield MultiDataSet(
                [f[sl] for f in self.mds.features],
                [l[sl] for l in self.mds.labels],
                cut(self.mds.features_masks),
                cut(self.mds.labels_masks),
            )

    def batch_size(self):
        return self.batch

    def total_examples(self):
        return self.mds.num_examples()
