"""Normalization layers: batch normalization and local response normalization.

Reference impls: nn/layers/normalization/BatchNormalization.java (+
CudnnBatchNormalizationHelper) and LocalResponseNormalization.java (+ cuDNN
helper). Both compile to fused XLA element-wise/reduction code here; no
helper SPI required for the base path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.registry import LayerContext, register_layer
from deeplearning4j_tpu.ops.activations import apply_activation
from deeplearning4j_tpu.ops.helpers import HelperError, get_helper


# -- batch normalization -----------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_train(x, gamma, beta, eps):
    """Fused training-mode batch norm with a hand-written VJP.

    jnp.mean/jnp.var upcast sub-f32 inputs to f32 internally, and autodiff
    of that pattern drags f32 activation-sized cotangents through the whole
    backward pass (2x HBM traffic on a bandwidth-bound op — measured 15%
    vs 40%+ train-step MFU on ResNet-50/v5e). Here every full-size tensor
    stays in x.dtype; only per-channel statistics are f32.
    """
    y, _, mean, var = _bn_train_fwd_res(x, gamma, beta, eps)
    return y, mean, var


def _acc_dtype(dtype):
    """Statistics accumulator dtype: f32, or f64 when the network itself
    runs f64 (the gradient-check configuration)."""
    return jnp.promote_types(dtype, jnp.float32)


def _sum_to_f32(x2, n):
    """Column sums of a [n, c] tensor with f32 accumulation WITHOUT an
    explicit upcast: a dot against a ones vector with
    preferred_element_type=f32. Crucial on TPU: reduce(convert(x)) makes
    XLA's bf16-propagation keep the PRODUCER of x (the conv output) in
    f32, doubling HBM traffic for the whole residual trunk — the dot
    keeps every stored tensor bf16 and runs the accumulation on the MXU."""
    ones = jnp.ones((n,), x2.dtype)
    return lax.dot_general(
        ones, x2, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _bn_stats(x):
    """Per-channel mean/var in the accumulator dtype. bf16 inputs use a
    centered two-pass MXU-dot reduction (f32 accumulation, no full-size
    f32 tensor); f32/f64 (gradient-check) inputs use the plain stable
    two-pass form."""
    if x.dtype == jnp.bfloat16:
        mean, var, _, _ = _bn_stats_centered(x)
        return mean, var
    axes = tuple(range(x.ndim - 1))
    xf = x.astype(_acc_dtype(x.dtype))
    mean = jnp.mean(xf, axis=axes)
    var = jnp.mean(jnp.square(xf - mean), axis=axes)
    return mean, var


def _bn_stats_centered(x):
    """bf16 statistics without catastrophic cancellation: first pass gets
    the mean (bf16 dot, f32 accumulation); xc = x - bf16(mean) is EXACT in
    bf16 wherever x is within 2x of the mean (Sterbenz), so the residual
    terms E[xc^2] and E[xc] are both small and their difference is safe in
    f32 — unlike raw E[x^2]-E[x]^2, which loses everything for
    large-mean/small-variance channels. Returns (mean, var, xc, delta)
    with mean = true mean (f32), delta = mean - bf16(mean) so that
    x - mean == xc - delta."""
    c = x.shape[-1]
    n = x.size // c
    x2 = x.reshape(n, c)
    mean = _sum_to_f32(x2, n) / n
    mean_b = mean.astype(x.dtype)
    xc = x - jnp.broadcast_to(mean_b, x.shape)
    xc2 = xc.reshape(n, c)
    mu_r = _sum_to_f32(xc2, n) / n            # == delta up to f32 rounding
    var = jnp.maximum(_sum_to_f32(xc2 * xc2, n) / n - mu_r * mu_r, 0.0)
    delta = mean - mean_b.astype(jnp.float32)
    return mean, var, xc, delta


def _bn_train_fwd_res(x, gamma, beta, eps):
    acc = _acc_dtype(x.dtype)
    if x.dtype == jnp.bfloat16:
        mean, var, xc, delta = _bn_stats_centered(x)
        inv = lax.rsqrt(var + eps)
        scale = gamma.astype(acc) * inv
        # y = scale*(x - mean) + beta = scale*(xc - delta) + beta
        shift = beta.astype(acc) - delta * scale
        y = xc * scale.astype(x.dtype) + shift.astype(x.dtype)
        # residual saves X (already materialized as the producing conv's
        # output) + the bf16 mean, NOT xc: the backward recomputes
        # xc = x - bf16(mean) in-register, bit-identical (bf16 subtract
        # is deterministic). Measured NEUTRAL on the ResNet-50 bench
        # (48.8 ms/step either way — XLA rematerializes the centered
        # tensor itself); kept because it states the true data
        # dependency instead of relying on that remat
        return y, (x, gamma, mean.astype(x.dtype), delta, inv), mean, var
    mean, var = _bn_stats(x)
    inv = lax.rsqrt(var + eps)
    scale = gamma.astype(acc) * inv
    shift = beta.astype(acc) - mean * scale
    y = x * scale.astype(x.dtype) + shift.astype(x.dtype)
    return y, (x, gamma, mean, None, inv), mean, var


def _bn_train_fwd(x, gamma, beta, eps):
    y, res, mean, var = _bn_train_fwd_res(x, gamma, beta, eps)
    return (y, mean, var), res


def _bn_train_bwd(eps, res, cts):
    """Standard BN backward, per-channel coefficients in f32, full-size
    math in x.dtype. The mean/var outputs feed the (non-trainable) running
    EMA only, so their cotangents are dropped — matching the reference,
    where global stats never receive gradient
    (BatchNormalization.java running mean/var are state, not params)."""
    g, _, _ = cts
    x, gamma, mean_saved, delta, inv = res
    g = g.astype(x.dtype)
    c = x.shape[-1]
    n = x.size // c
    acc = _acc_dtype(x.dtype)
    if x.dtype == jnp.bfloat16:
        # recompute xc = x - bf16(mean) in-register (see fwd residual
        # note); center = delta so x - mean == xc - delta and sums of
        # g*xc stay small — no large-mean cancellation in sum_gx
        xc = x - jnp.broadcast_to(mean_saved, x.shape)
        center = delta
        x_for_dx = xc
    else:
        center = mean_saved
        x_for_dx = x
    # fused Pallas pullback when registered + supported: one reduce pass
    # (both per-channel sums) + one apply pass instead of three separate
    # XLA re-reads of the saved activation; same kill-switch/auto-disable
    # containment as the forward helpers — a raising kernel disables
    # itself and the builtin reductions below finish the same backward
    helper = get_helper("bn_backward", x_shape=tuple(x.shape),
                        dtype=x.dtype, training=True)
    if helper is not None:
        try:
            dx, dgamma, dbeta = helper(g, x_for_dx, center, gamma, inv, n)
            return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)
        except HelperError:
            pass  # helper auto-disabled itself; builtin path below
    if x.dtype == jnp.bfloat16:
        g2 = g.reshape(n, c)
        x2 = x_for_dx.reshape(n, c)
        sum_g = _sum_to_f32(g2, n)
        sum_gx = _sum_to_f32(g2 * x2, n) - center * sum_g
    else:
        axes = tuple(range(x.ndim - 1))
        gf = g.astype(acc)
        xf = x.astype(acc)
        sum_g = jnp.sum(gf, axis=axes)
        sum_gx = jnp.sum(gf * xf, axis=axes) - center * sum_g
    dgamma = (inv * sum_gx).astype(gamma.dtype)
    dbeta = sum_g.astype(gamma.dtype)
    gamma_f = gamma.astype(acc)
    c1 = gamma_f * inv
    c3 = gamma_f * inv * inv * inv * sum_gx / n
    # dx = c1*g - c3*(x - mean) - c1*sum_g/n, with (x - mean) =
    # x_for_dx - center in both branches (bf16: xc - delta; else: x - mean)
    c0 = -(c1 * sum_g / n) + c3 * center
    dx = (c1.astype(x.dtype) * g - c3.astype(x.dtype) * x_for_dx
          + c0.astype(x.dtype))
    return dx, dgamma, dbeta


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)

def batchnorm_init(key, conf: L.BatchNormalization, dtype):
    n = int(conf.n_in)
    return {
        "gamma": jnp.full((n,), conf.gamma, dtype),
        "beta": jnp.full((n,), conf.beta, dtype),
    }


def batchnorm_state(conf: L.BatchNormalization, dtype):
    n = int(conf.n_in)
    return {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}


def batchnorm_forward(conf: L.BatchNormalization, params, x, ctx: LayerContext):
    """Normalizes over all axes but the last (channels for NHWC, features
    for 2d). Training uses batch statistics and EMA-updates the running
    stats (decay semantics as the reference: global = decay*global +
    (1-decay)*batch); inference uses the running stats."""
    eps = conf.eps
    state = ctx.state or {}
    if ctx.training:
        if conf.lock_gamma_beta:
            # locked = fixed at the conf constants, not trainable
            # (reference: BatchNormalization.java lockGammaBeta applies
            # the configured gamma/beta without learning them)
            c = params["gamma"].shape[0] if "gamma" in params else x.shape[-1]
            gamma = jnp.full((c,), conf.gamma, _acc_dtype(x.dtype))
            beta = jnp.full((c,), conf.beta, _acc_dtype(x.dtype))
        else:
            gamma, beta = params["gamma"], params["beta"]
        # vendor-kernel plugin point (the CudnnBatchNormalizationHelper
        # analog): when this input is a stashed conv+stats-epilogue output
        # (ops/pallas_conv_bn.py), the fused normalize kernel consumes the
        # precomputed statistics — one read of x instead of two. The probe
        # matches by tensor identity, so anything else falls through to
        # the built-in fused path below.
        y = mean = var = None
        helper = get_helper("batch_norm", x=x, training=True)
        if helper is not None:
            try:
                y, mean, var = helper(x, gamma, beta, eps)
            except HelperError:
                y = None
        if y is None:
            y, mean, var = _bn_train(x, gamma, beta, eps)
        d = conf.decay
        mean = lax.stop_gradient(mean)
        var = lax.stop_gradient(var)
        st_mean = state.get("mean")
        st_var = state.get("var")
        acc = _acc_dtype(x.dtype)
        new_state = {
            "mean": (d * st_mean.astype(acc) + (1 - d) * mean
                     ).astype(st_mean.dtype) if st_mean is not None
                    else mean,
            "var": (d * st_var.astype(acc) + (1 - d) * var
                    ).astype(st_var.dtype) if st_var is not None
                   else var,
        }
        return y, new_state
    mean = state.get("mean")
    var = state.get("var")
    if mean is None:
        mean, var = _bn_stats(x)
    inv = lax.rsqrt(var.astype(_acc_dtype(x.dtype)) + eps)
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    if conf.lock_gamma_beta:
        y = jnp.asarray(conf.gamma, x.dtype) * xhat \
            + jnp.asarray(conf.beta, x.dtype)
    else:
        y = params["gamma"].astype(x.dtype) * xhat + params["beta"].astype(x.dtype)
    return y, None


def batchnorm_order(conf):
    return ("gamma", "beta")


register_layer(
    L.BatchNormalization, batchnorm_init, batchnorm_forward,
    order_fn=batchnorm_order, state_fn=batchnorm_state,
)


# -- local response normalization -------------------------------------------

def _no_params(key, conf, dtype):
    return {}


def lrn_forward(conf: L.LocalResponseNormalization, params, x, ctx: LayerContext):
    """Cross-channel LRN on NHWC: y = x / (k + alpha*sum_window(x^2))^beta
    (reference: LocalResponseNormalization.java; window of size n centered
    on each channel). reduce_window over the channel axis."""
    n = int(conf.n)
    half = n // 2
    sq = x * x
    window = (1, 1, 1, n)
    strides = (1, 1, 1, 1)
    padding = [(0, 0), (0, 0), (0, 0), (half, n - 1 - half)]
    ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides, padding)
    denom = (conf.k + conf.alpha * ssum) ** conf.beta
    return x / denom, None


register_layer(L.LocalResponseNormalization, _no_params, lrn_forward)
