"""REST model-inference server — the serving front-end the reference left
to users (ParallelInference.java was always embedded behind someone's
HTTP layer; here the layer ships with the framework, sibling of
serving/knnserver.py on the same utils/jsonhttp scaffold).

Wraps a MultiLayerNetwork or ComputationGraph in a bucketed, pipelined
ParallelInference (parallel/inference.py — BATCHED mode fuses concurrent
requests, pads each fused group to a fixed bucket so only ~log2(B)
forward traces ever compile, and overlaps host batch assembly with
device execution). Routes:

    POST /predict  {"features": [[...], ...]}   -> {"predictions": [...]}
                   (a single flat example is also accepted and returns a
                    single prediction row; a multi-output graph returns
                    one predictions entry per output head)
    GET  /health   -> {"status": "ok", "model": ..., "feature_shape": ...}
    GET  /metrics  -> {"requests", "examples", "batches", "queue_depth",
                       "buckets", "bucket_hits", "oversized",
                       "forward_compiles", "latency_ms":
                       {"count", "mean_ms", "p50_ms", "p99_ms"}, ...}
    GET  /metrics?format=prometheus
                   -> text exposition of the process-global registry
                      (utils/metrics.py): serving series plus any
                      training-side fit_step_* / compile_total /
                      helper_* counters living in the same process
    GET  /trace    -> recent host spans as JSONL (utils/tracing.py);
                      ?format=chrome returns a chrome://tracing document

Knobs (constructor and CLI flags): `max_batch_size`, `batch_timeout_ms`,
`buckets`, `warmup_shape` (precompiles every bucket before the port
opens, so first requests never pay a compile).
"""

from __future__ import annotations

import argparse
import json
import logging
import time
import urllib.parse
from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.parallel.inference import (
    InferenceMode,
    ParallelInference,
    ReplicaPool,
    RequestValidationError,
)
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import tracing as _tracing
from deeplearning4j_tpu.utils.jsonhttp import JsonHttpServer, json_response
from deeplearning4j_tpu.utils.latency import LatencyTracker

logger = logging.getLogger("deeplearning4j_tpu")


class InferenceServer:
    def __init__(
        self,
        model,
        port: int = 0,
        mesh=None,
        inference_mode: str = InferenceMode.BATCHED,
        max_batch_size: int = 64,
        batch_timeout_ms: float = 2.0,
        buckets: Optional[Sequence[int]] = None,
        warmup_shape: Optional[Sequence[int]] = None,
        health_stall_after: float = 30.0,
        n_replicas: int = 1,
    ):
        # n_replicas >= 2 turns on the self-healing pool: each replica's
        # collector/dispatcher heartbeats are watched separately, an
        # unhealthy replica is evicted (only its in-flight requests fail;
        # queued work re-routes to a sibling with no user-visible error)
        # and respawned — the eviction/respawn cycle shows up in
        # component_health transitions and serving_replica_* counters on
        # the same /metrics scrape as the traffic series
        if int(n_replicas) > 1:
            self.inference = ReplicaPool(
                model, n_replicas=int(n_replicas), mesh=mesh,
                inference_mode=inference_mode,
                max_batch_size=max_batch_size,
                batch_timeout_ms=batch_timeout_ms, buckets=buckets,
                health_stall_after=health_stall_after,
            )
        else:
            self.inference = ParallelInference(
                model, mesh, inference_mode, max_batch_size,
                batch_timeout_ms, buckets,
                health_stall_after=health_stall_after,
            )
        if warmup_shape is not None:
            self.inference.warmup(warmup_shape)
        self.latency = LatencyTracker()
        # request latency also lands in the shared registry so one
        # Prometheus scrape carries serving AND training series
        self._m_latency = _metrics.get_registry().histogram(
            "serving_request_seconds",
            "end-to-end /predict latency (admission to result)").labels()
        self._server = JsonHttpServer(get=self._get, post=self._post,
                                      port=port)

    @property
    def port(self) -> int:
        return self._server.port

    def metrics(self) -> dict:
        m = self.inference.metrics()
        # JSON object keys must be strings; bucket sizes are ints
        m["bucket_hits"] = {str(k): v for k, v in m["bucket_hits"].items()}
        m["latency_ms"] = self.latency.snapshot()
        return m

    # -- request handling ----------------------------------------------------

    def _get(self, path, body, headers):
        parsed = urllib.parse.urlparse(path)
        route = parsed.path
        query = urllib.parse.parse_qs(parsed.query)
        fmt = (query.get("format") or [""])[0]
        if route == "/health":
            # the aggregated health model (utils/health): worst component
            # status, with per-component stall detail. 503 when UNHEALTHY
            # so load balancers stop routing here (the replica-eviction
            # hook); degraded stays 200 — shedding, not eviction.
            shape = self.inference._expected_shape
            h = _health.get_health().status()
            code = 503 if h["status"] == _health.UNHEALTHY else 200
            return json_response({
                "status": h["status"],
                "components": h["components"],
                "model": type(self.inference.model).__name__,
                "feature_shape": None if shape is None else list(shape),
            }, code)
        if route == "/metrics":
            if fmt == "prometheus":
                text = _metrics.get_registry().to_prometheus()
                return 200, "text/plain; version=0.0.4", text.encode()
            if fmt == "registry":
                # the registry's JSON snapshot (same series as the
                # prometheus exposition, machine-readable) — what
                # `cli metrics --watch --url` diffs per tick
                return json_response(_metrics.get_registry().snapshot())
            return json_response(self.metrics())
        if route == "/trace":
            # recent host spans — JSONL by default (tail-able), or the
            # chrome://tracing document with ?format=chrome
            tracer = _tracing.get_tracer()
            if fmt == "chrome":
                return json_response(tracer.to_chrome_trace())
            n_raw = (query.get("n") or [None])[0]
            try:
                n = None if n_raw is None else max(0, int(n_raw))
            except ValueError:
                n = None
            return 200, "application/x-ndjson", tracer.to_jsonl(n).encode()
        return None

    def _post(self, path, body, headers):
        if path != "/predict":
            return None
        req = json.loads(body or b"{}")
        if "features" not in req:
            return json_response({"error": "missing 'features'"}, 400)
        try:
            feats = np.asarray(req["features"], np.float32)
        except (ValueError, TypeError) as e:  # ragged / non-numeric
            return json_response({"error": f"bad features: {e}"}, 400)
        if feats.ndim == 0 or feats.size == 0:
            return json_response(
                {"error": "features must be a non-empty example array"}, 400)
        single = feats.ndim == 1
        if single:
            feats = feats[None]
        t0 = time.perf_counter()
        try:
            with _tracing.span("serve/predict", examples=int(feats.shape[0])):
                out = self.inference.output(feats)
        except RequestValidationError as e:  # the client's fault
            return json_response({"error": str(e)}, 400)
        except Exception as e:
            # anything else (shutdown race, model/XLA failure — including
            # server-side ValueErrors) is a server fault: 500, so
            # clients/load-balancers retry or fail over (JsonHttpServer's
            # catch-all would mislabel it a 400)
            return json_response({"error": f"{type(e).__name__}: {e}"}, 500)
        dt = time.perf_counter() - t0
        self.latency.record(dt)
        self._m_latency.observe(dt)
        if isinstance(out, list):  # multi-output graph: one entry per head
            preds = [np.asarray(o)[0].tolist() if single
                     else np.asarray(o).tolist() for o in out]
        else:
            out = np.asarray(out)
            preds = (out[0] if single else out).tolist()
        return json_response({"predictions": preds})

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> int:
        return self._server.start()

    def stop(self):
        self._server.stop()
        self.inference.shutdown()

    def join(self):
        self._server.join()


def main(argv=None):
    """CLI: serve a saved model zip / Keras h5 over REST.

        python -m deeplearning4j_tpu.serving.inference_server \
            --modelPath model.zip --port 9100 --maxBatchSize 64 \
            --batchTimeoutMs 2 --warmupShape 784
    """
    ap = argparse.ArgumentParser(description="model inference REST server")
    ap.add_argument("--modelPath", required=True)
    ap.add_argument("--port", type=int, default=9100)
    ap.add_argument("--maxBatchSize", type=int, default=64)
    ap.add_argument("--batchTimeoutMs", type=float, default=2.0)
    ap.add_argument("--buckets", default=None,
                    help="comma-separated bucket sizes (default: powers of "
                         "two up to maxBatchSize)")
    ap.add_argument("--warmupShape", default=None,
                    help="comma-separated feature shape to precompile all "
                         "buckets before the port opens, e.g. 784 or 28,28,1")
    ap.add_argument("--replicas", type=int, default=1,
                    help=">=2 serves through a self-healing ReplicaPool: "
                         "unhealthy replicas are evicted and respawned")
    args = ap.parse_args(argv)
    from deeplearning4j_tpu.cli import guess_and_load_model

    model = guess_and_load_model(args.modelPath)
    buckets = (None if args.buckets is None
               else [int(b) for b in args.buckets.split(",")])
    warmup = (None if args.warmupShape is None
              else tuple(int(d) for d in args.warmupShape.split(",")))
    server = InferenceServer(
        model, port=args.port, max_batch_size=args.maxBatchSize,
        batch_timeout_ms=args.batchTimeoutMs, buckets=buckets,
        warmup_shape=warmup, n_replicas=args.replicas,
    )
    # operator surface: opt in to real log output, then announce through
    # the package logger (library code never prints — lint CC006)
    from deeplearning4j_tpu import configure_logging

    if all(isinstance(h, logging.NullHandler) for h in logger.handlers):
        configure_logging()
    port = server.start()
    logger.info("inference server listening on :%d (buckets %s)",
                port, server.inference.buckets)
    try:
        server.join()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
