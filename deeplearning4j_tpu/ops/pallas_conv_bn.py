"""Pallas conv + BN-statistics epilogue fusion — the CudnnConvolutionHelper/
CudnnBatchNormalizationHelper pair for the ResNet trunk.

Why: PROFILE_resnet50.md shows the train step is bandwidth-bound, with
16.4 ms of a 48.8 ms step spent on batch-norm statistics/normalization
traffic over the residual trunk (`convert_reduce_fusion` = 25.8 ms/step).
XLA materializes each conv output to HBM, then re-reads the full tensor
for the per-channel statistics reduction, then re-reads it AGAIN for the
normalize. This module closes one of those reads: the conv kernel computes
per-channel sum / sum-of-squares in f32 as an epilogue over each output
tile while it is still in VMEM, so the stats cost no extra HBM traffic at
all; a second fused normalize(+ReLU) kernel then performs the one
remaining read.

Two helper slots (ops/helpers.py), mirroring the reference's plugin pair
(CudnnConvolutionHelper.java:345, BatchNormalizationHelper.java:29):

- "conv2d":     `_conv2d_helper` — conv forward with the stats epilogue.
  The stats ride to the downstream BatchNormalization layer through a
  producer→consumer stash keyed by tensor identity: within one trace the
  conv's output object IS the BN layer's input object (compgraph passes
  activations through untouched), so the match is exact and anything in
  between (an activation, a residual add) breaks it safely.
- "batch_norm": `_bn_helper` — fused normalize from the stashed stats,
  with a deferred-ReLU hook: when the very next layer is a ReLU
  ActivationLayer, it swaps in the normalize+ReLU variant of the kernel
  and the plain-normalize pallas_call is dead-code-eliminated by XLA.

Scope (checked by the probes; everything else falls back silently to the
XLA lowering, exactly like the cuDNN checkSupported fallback): NHWC,
bf16 on real TPU, training mode, bias-free identity-activation convs with
kernel 1x1 (stride 1 or 2) or 3x3 (stride 1), SAME padding, no dilation
— the shapes of every ResNet bottleneck conv except the 7x7 stem and the
three stage-entry 3x3/s2 convs.

Backward is a hand-written custom_vjp pair: the conv pullback is the
standard pair of transposed XLA convolutions (jax.linear_transpose of the
reference lowering — already MXU-shaped; Pallas buys nothing there), and
the BN pullback reuses the fused-BN VJP structure of nn/layers/norm.py
(per-channel coefficients in the f32 accumulator dtype, every full-size
tensor in x.dtype). The stats outputs are stop_gradient'ed at the stash:
the BN backward's dx is the TOTAL derivative including the statistics
paths (same composite as norm.py's `_bn_train`), so routing any cotangent
through the stats tensors as well would double-count.
"""

from __future__ import annotations

import logging
from collections import deque
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger("deeplearning4j_tpu")

_INTERPRET = False  # flipped by tests on CPU (same pattern as pallas_lstm)

_DIMS2D = ("NHWC", "HWIO", "NHWC")


# -- producer→consumer stashes ----------------------------------------------
#
# Entries are matched by `is` on the traced value, so they can only ever
# connect a conv to the BN (or a BN to the ReLU) that consumes that exact
# tensor inside the same trace. Bounded deques: unmatched entries (a conv
# whose consumer is not a BN, an abandoned trace) age out instead of
# accumulating tracer references.

_STATS_STASH: deque = deque(maxlen=8)
_RELU_STASH: deque = deque(maxlen=8)


def _stash_pop(dq: deque, x):
    """Remove and return the entry whose key tensor IS x. Removal is by
    index — deque.remove would compare entries with ==, which on traced
    arrays of unequal shapes raises instead of answering False."""
    for i, entry in enumerate(dq):
        if entry[0] is x:
            del dq[i]
            return entry
    return None


def _stash_stats(y, s1, s2) -> None:
    _STATS_STASH.append((y, s1, s2))


def take_stats(x):
    """(sum, sum_sq) f32 per-channel stats stashed for exactly this tensor,
    removing the entry; None when x is not a stashed conv output."""
    entry = _stash_pop(_STATS_STASH, x)
    return None if entry is None else (entry[1], entry[2])


def peek_stats(x) -> bool:
    return any(entry[0] is x for entry in _STATS_STASH)


def _stash_relu(y, thunk) -> None:
    _RELU_STASH.append((y, thunk))


def take_fused_relu(x):
    """The normalize+ReLU variant of a stashed BN output, or None. The
    plain-normalize pallas_call that produced x becomes dead code once its
    only consumer switches to the fused variant — XLA eliminates it."""
    entry = _stash_pop(_RELU_STASH, x)
    if entry is None:
        return None
    try:
        return entry[1]()
    except Exception as e:  # never let the fusion shortcut kill a layer
        logger.warning("fused BN+ReLU thunk failed (%s); applying "
                       "plain ReLU instead", e)
        return None


# -- tiling helpers ----------------------------------------------------------

def _row_tile(m: int, cap: int = 512) -> int:
    """Largest power-of-two row tile <= cap dividing m (ResNet row counts
    are highly 2-adic: N*H*W = 128*56*56 etc; tiny test shapes land on a
    smaller divisor, worst case 1)."""
    t = cap
    while t > 1 and m % t:
        t //= 2
    return t


def _acc_dtype(dtype):
    """f32 accumulators, or f64 when the whole check runs f64 (the
    gradient-check configuration) — matches nn/layers/norm.py."""
    return jnp.promote_types(dtype, jnp.float32)


# -- 1x1 conv (pointwise matmul) with stats epilogue -------------------------

def _mm_stats_kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        s1_ref[:] = jnp.zeros_like(s1_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)

    acc_dt = s1_ref.dtype
    y = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=acc_dt)
    yb = y.astype(y_ref.dtype)
    y_ref[:] = yb
    # Epilogue over the tile while it is still in VMEM. Statistics are of
    # the STORED (rounded) tensor — what the normalize will actually read
    # — not the f32 pre-rounding accumulator.
    yf = yb.astype(acc_dt)
    s1_ref[:] += jnp.sum(yf, axis=0, keepdims=True)
    s2_ref[:] += jnp.sum(yf * yf, axis=0, keepdims=True)


def _mm_stats_call(x2, w2):
    m, cin = x2.shape
    cout = w2.shape[1]
    acc = _acc_dtype(x2.dtype)
    # big-channel shapes get a smaller row tile so weights + double-buffered
    # row tiles stay inside VMEM (probe re-checks the same budget)
    tm = _row_tile(m, 128 if cin * cout >= 1024 * 1024 else 512)
    y2, s1, s2 = pl.pallas_call(
        _mm_stats_kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, cin), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((cin, cout), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tm, cout), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, cout), x2.dtype),
            jax.ShapeDtypeStruct((1, cout), acc),
            jax.ShapeDtypeStruct((1, cout), acc),
        ],
        interpret=_INTERPRET,
    )(x2, w2)
    return y2, s1, s2


# -- 3x3 stride-1 SAME conv with stats epilogue ------------------------------

def _c3_stats_kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref):
    n = pl.program_id(0)

    @pl.when(n == 0)
    def _():
        s1_ref[:] = jnp.zeros_like(s1_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)

    acc_dt = s1_ref.dtype
    h, w = y_ref.shape[1], y_ref.shape[2]
    cout = y_ref.shape[3]
    acc = jnp.zeros((h, w, cout), acc_dt)
    x = x_ref[0]
    # 9 shifted whole-image dots accumulated in VMEM. The SAME-padding
    # halo is handled by clipping each shift to its valid region (static
    # slices) instead of pre-padding the input — a jnp.pad outside the
    # kernel would materialize a full padded copy to HBM, spending the
    # very read the stats epilogue saves.
    for a in (-1, 0, 1):
        i0, i1 = max(0, -a), h - max(0, a)
        for b in (-1, 0, 1):
            j0, j1 = max(0, -b), w - max(0, b)
            part = lax.dot_general(
                x[i0 + a:i1 + a, j0 + b:j1 + b, :],
                w_ref[a + 1, b + 1],
                (((2,), (0,)), ((), ())),
                preferred_element_type=acc_dt,
            )
            # zero-extend the clipped partial back to (h, w) and add —
            # in-register pad; .at[...].add would capture index constants
            # the kernel tracer rejects
            acc = acc + lax.pad(
                part, jnp.asarray(0, acc_dt),
                ((i0, h - i1, 0), (j0, w - j1, 0), (0, 0, 0)))
    yb = acc.astype(y_ref.dtype)
    y_ref[0] = yb
    yf = yb.astype(acc_dt).reshape(h * w, cout)
    s1_ref[:] += jnp.sum(yf, axis=0, keepdims=True)
    s2_ref[:] += jnp.sum(yf * yf, axis=0, keepdims=True)


def _c3_stats_call(x, w):
    n, h, wd, cin = x.shape
    cout = w.shape[3]
    acc = _acc_dtype(x.dtype)
    y, s1, s2 = pl.pallas_call(
        _c3_stats_kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, wd, cin), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 3, cin, cout), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, h, wd, cout), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wd, cout), x.dtype),
            jax.ShapeDtypeStruct((1, cout), acc),
            jax.ShapeDtypeStruct((1, cout), acc),
        ],
        interpret=_INTERPRET,
    )(x, w)
    return y, s1, s2


# -- fused conv + stats op (custom_vjp) --------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2,))
def conv2d_bn_stats(x, w, strides):
    """NHWC conv (SAME, bias-free) returning (y, sum, sum_sq) where the
    per-channel f32 statistics are computed as a VMEM epilogue of the conv
    output tiles — zero extra HBM traffic for the reduction.

    x: [N,H,W,Cin]; w: [kh,kw,Cin,Cout] with (kh,kw) in {(1,1),(3,3)};
    strides: static (sh,sw) — (1,1), or (2,2) for 1x1 kernels.

    The statistics outputs carry NO gradient (see module docstring: the
    paired `bn_apply` backward computes the total dx including the stats
    paths). Consume them via the Helper SPI wiring or stop_gradient them.
    """
    y, s1, s2 = _conv_fwd_impl(x, w, strides)
    return y, s1, s2


def _conv_fwd_impl(x, w, strides):
    kh, kw = int(w.shape[0]), int(w.shape[1])
    cout = int(w.shape[3])
    if (kh, kw) == (1, 1):
        sh, sw = strides
        if (sh, sw) != (1, 1):
            # SAME 1x1/s: output pixel (i,j) samples x[i*s, j*s] exactly
            x = x[:, ::sh, ::sw, :]
        n, h, wd, cin = x.shape
        y2, s1, s2 = _mm_stats_call(x.reshape(n * h * wd, cin),
                                    w.reshape(cin, cout))
        return y2.reshape(n, h, wd, cout), s1[0], s2[0]
    # 3x3 stride 1 SAME: full image per grid step, halo clipped in-kernel
    y, s1, s2 = _c3_stats_call(x, w)
    return y, s1[0], s2[0]


def _conv_fwd(x, w, strides):
    out = _conv_fwd_impl(x, w, strides)
    return out, (x, w)


def _conv_bwd(strides, res, cts):
    """Pullback = the two transposed convolutions of the reference XLA
    lowering (linear_transpose instantiates no forward pass). ds1/ds2 are
    structurally zero — the stats are stop_gradient'ed at the stash and
    bn_apply's dx is the total derivative — so they are dropped here."""
    x, w = res
    dy, _, _ = cts

    def conv_x(xx):
        return lax.conv_general_dilated(
            xx, w, window_strides=strides, padding="SAME",
            dimension_numbers=_DIMS2D)

    def conv_w(ww):
        return lax.conv_general_dilated(
            x, ww, window_strides=strides, padding="SAME",
            dimension_numbers=_DIMS2D)

    dx, = jax.linear_transpose(conv_x, x)(dy)
    dw, = jax.linear_transpose(conv_w, w)(dy)
    return dx, dw


conv2d_bn_stats.defvjp(_conv_fwd, _conv_bwd)


# -- fused normalize(+ReLU) consumer (custom_vjp) ----------------------------

def _norm_kernel_relu(x_ref, mb_ref, sc_ref, sh_ref, y_ref):
    xc = x_ref[:] - mb_ref[:]
    y = xc * sc_ref[:].astype(x_ref.dtype) + sh_ref[:].astype(x_ref.dtype)
    y_ref[:] = jnp.maximum(y, jnp.zeros_like(y))


def _norm_kernel(x_ref, mb_ref, sc_ref, sh_ref, y_ref):
    xc = x_ref[:] - mb_ref[:]
    y_ref[:] = xc * sc_ref[:].astype(x_ref.dtype) \
        + sh_ref[:].astype(x_ref.dtype)


def _norm_call(x2, mean_b, scale, shift, relu):
    """y = (x - mean_b)*scale + shift, one fused pass. Centered BEFORE the
    scale exactly like norm.py's `_bn_train`: x - bf16(mean) is exact near
    the mean (Sterbenz), so low-precision rounding applies to the
    deviation, not to mean*scale-sized intermediates."""
    m, c = x2.shape
    tm = _row_tile(m)
    return pl.pallas_call(
        _norm_kernel_relu if relu else _norm_kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, c), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tm, c), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, c), x2.dtype),
        interpret=_INTERPRET,
    )(x2, mean_b, scale, shift)


def _col_sums(x2, acc_dt):
    """Column sums of [n, c] with accumulator-dtype accumulation via a dot
    against ones — the MXU form norm.py's `_sum_to_f32` uses, generalized
    to f64 for the gradient-check configuration."""
    ones = jnp.ones((x2.shape[0],), x2.dtype)
    return lax.dot_general(ones, x2, (((0,), (0,)), ((), ())),
                           preferred_element_type=acc_dt)


@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def bn_apply(x, s1, s2, gamma, beta, eps, n, relu):
    """Training-mode batch norm from precomputed raw moments: one fused
    read of x (normalize + optional ReLU in a single Pallas pass) instead
    of XLA's reduce-then-normalize double read. Returns (y, mean, var)
    exactly like norm.py's `_bn_train`; mean/var feed the running-EMA
    state only. n = number of reduced elements (x.size / channels);
    eps/n/relu are static."""
    out, _ = _bn_fwd(x, s1, s2, gamma, beta, eps, n, relu)
    return out


def _bn_fwd(x, s1, s2, gamma, beta, eps, n, relu):
    acc = _acc_dtype(x.dtype)
    c = x.shape[-1]
    mean = s1.astype(acc) / n
    var = jnp.maximum(s2.astype(acc) / n - mean * mean, 0.0)
    inv = lax.rsqrt(var + eps)
    scale = gamma.astype(acc) * inv
    # centered application (norm.py's bf16 form): y = (x - bf16(mean))
    # * scale + (beta - delta*scale), with delta the mean's rounding error
    mean_b = mean.astype(x.dtype)
    delta = mean - mean_b.astype(acc)
    shift = beta.astype(acc) - delta * scale
    y2 = _norm_call(x.reshape(n, c), mean_b[None, :], scale[None, :],
                    shift[None, :], relu)
    y = y2.reshape(x.shape)
    return (y, mean, var), (x, gamma, mean, inv, y)


def _bn_bwd(eps, n, relu, res, cts):
    """The fused-BN VJP of nn/layers/norm.py (`_bn_train_bwd`), extended
    with the ReLU gate: per-channel coefficients in the accumulator dtype,
    every full-size tensor in x.dtype; bf16 uses the centered reduction
    (x - bf16(mean), exact by Sterbenz near the mean) so sum_gx never
    cancels catastrophically. mean/var cotangents are dropped — they feed
    the non-trainable running EMA, as in the reference."""
    g, _, _ = cts
    x, gamma, mean, inv, y = res
    g = g.astype(x.dtype)
    if relu:
        g = jnp.where(y > 0, g, jnp.zeros_like(g))
    c = x.shape[-1]
    acc = _acc_dtype(x.dtype)
    if x.dtype == jnp.bfloat16:
        mean_b = mean.astype(x.dtype)
        delta = mean - mean_b.astype(acc)
        xc = x - jnp.broadcast_to(mean_b, x.shape)
        g2 = g.reshape(n, c)
        x2 = xc.reshape(n, c)
        sum_g = _col_sums(g2, acc)
        sum_gx = _col_sums(g2 * x2, acc) - delta * sum_g
        center = delta
        x_for_dx = xc
    else:
        g2 = g.astype(acc).reshape(n, c)
        x2 = x.astype(acc).reshape(n, c)
        sum_g = jnp.sum(g2, axis=0)
        sum_gx = jnp.sum(g2 * x2, axis=0) - mean * sum_g
        center = mean
        x_for_dx = x
    dgamma = (inv * sum_gx).astype(gamma.dtype)
    dbeta = sum_g.astype(gamma.dtype)
    gamma_f = gamma.astype(acc)
    c1 = gamma_f * inv
    c3 = gamma_f * inv * inv * inv * sum_gx / n
    c0 = -(c1 * sum_g / n) + c3 * center
    dx = (c1.astype(x.dtype) * g - c3.astype(x.dtype) * x_for_dx
          + c0.astype(x.dtype))
    # dx is the TOTAL derivative (elementwise + both statistics paths);
    # the raw-moment inputs therefore receive zero cotangent.
    zs = jnp.zeros((c,), _acc_dtype(x.dtype))
    return dx, zs, zs, dgamma, dbeta


bn_apply.defvjp(_bn_fwd, _bn_bwd)


# -- Helper SPI wiring -------------------------------------------------------

_VMEM_BUDGET = 12 * 1024 * 1024  # leave headroom under the ~16MB/core VMEM


def _conv_vmem_ok(kernel, x_shape, n_in, n_out, itemsize) -> bool:
    if kernel == (3, 3):
        h, w = x_shape[1], x_shape[2]
        slab = h * w * n_in * itemsize  # one full input image
        out = h * w * n_out * itemsize
        accf = h * w * n_out * 4
        wgt = 9 * n_in * n_out * itemsize
        return 2 * (slab + out) + accf + wgt <= _VMEM_BUDGET
    wgt = n_in * n_out * itemsize
    tm = 128 if n_in * n_out >= 1024 * 1024 else 512
    tiles = 2 * tm * (n_in + n_out) * itemsize
    return wgt + tiles <= _VMEM_BUDGET


def conv_supported(*, kernel, stride, dilation, same, has_bias, activation,
                   dtype, n_in, n_out, x_shape, training, **_):
    """Probe for the "conv2d" slot. Whitelists exactly the ResNet-stage
    conv shapes the kernels cover; everything else (stem 7x7, stage-entry
    3x3/s2, biased or activated convs, inference) falls back to the XLA
    lowering — the cuDNN checkSupported pattern."""
    if not training or has_bias or not same:
        return False
    if activation not in (None, "identity"):
        return False
    if tuple(dilation) != (1, 1):
        return False
    k, s = tuple(kernel), tuple(stride)
    if k == (1, 1):
        if s not in ((1, 1), (2, 2)):
            return False
    elif k == (3, 3):
        if s != (1, 1):
            return False
    else:
        return False
    if _INTERPRET:  # CPU correctness tests: any float dtype / tiny channels
        return jnp.issubdtype(dtype, jnp.floating)
    if jax.default_backend() != "tpu" or dtype != jnp.bfloat16:
        return False
    # ResNet trunk channel counts tile the 128-lane registers cleanly
    if n_in % 64 or n_out % 64:
        return False
    return _conv_vmem_ok(k, x_shape, n_in, n_out, jnp.dtype(dtype).itemsize)


def bn_supported(*, x, training, **_):
    """Probe for the "batch_norm" slot: only engages when the input IS a
    stashed conv-epilogue output (identity match) — otherwise the built-in
    fused XLA path is already optimal (it needs the stats reduction
    anyway)."""
    if not training or not hasattr(x, "ndim") or x.ndim != 4:
        return False
    if _INTERPRET:
        return peek_stats(x)
    if jax.default_backend() != "tpu" or x.dtype != jnp.bfloat16:
        return False
    return peek_stats(x)


def _conv2d_helper(x, w, *, strides):
    y, s1, s2 = conv2d_bn_stats(x, w, tuple(int(s) for s in strides))
    # stop_gradient: the stats must never carry their own cotangent —
    # bn_apply's backward already accounts for them (module docstring)
    _stash_stats(y, lax.stop_gradient(s1), lax.stop_gradient(s2))
    return y


def _bn_helper(x, gamma, beta, eps):
    st = take_stats(x)
    if st is None:  # probe checked peek_stats; defensive
        raise RuntimeError("bn helper called without stashed conv stats")
    s1, s2 = st
    n = x.size // x.shape[-1]
    y, mean, var = bn_apply(x, s1, s2, gamma, beta, float(eps), n, False)
    # deferred ReLU: a downstream relu ActivationLayer swaps in the fused
    # variant; the plain-normalize call above then has no consumers and is
    # dead-code-eliminated at lowering
    _stash_relu(y, lambda: bn_apply(x, s1, s2, gamma, beta,
                                    float(eps), n, True)[0])
    return y, mean, var


def register():
    from deeplearning4j_tpu.ops.helpers import register_helper

    register_helper("conv2d", _conv2d_helper, conv_supported,
                    name="pallas_conv_bn_stats")
    register_helper("batch_norm", _bn_helper, bn_supported,
                    name="pallas_fused_bn_apply")


register()
