"""Batched distance kernels shared by the clustering/neighbor modules.

The reference computes distances point-at-a-time through ND4J accumulations
(clustering/algorithm/BaseClusteringAlgorithm.java, vptree/VPTree.java
distance calls). TPU-first, every distance is an [n, m] block computed as
matmuls: ||x - c||^2 = ||x||^2 + ||c||^2 - 2 x.c rides the MXU, and the
host only ever sees the reduced results (argmin/top-k).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

EPS = 1e-12

SUPPORTED = ("euclidean", "sqeuclidean", "manhattan", "cosinesimilarity", "dot")


def pairwise(x, y, distance: str):
    """[n, d] x [m, d] -> [n, m] distance/similarity block."""
    if distance in ("euclidean", "sqeuclidean"):
        x2 = jnp.sum(x * x, axis=1)[:, None]
        y2 = jnp.sum(y * y, axis=1)[None, :]
        d2 = jnp.maximum(x2 + y2 - 2.0 * (x @ y.T), 0.0)
        return jnp.sqrt(d2) if distance == "euclidean" else d2
    if distance == "manhattan":
        # no matmul form; still batched on-device
        return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)
    if distance == "cosinesimilarity":
        xn = x / jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True) + EPS)
        yn = y / jnp.sqrt(jnp.sum(y * y, axis=1, keepdims=True) + EPS)
        return xn @ yn.T
    if distance == "dot":
        return x @ y.T
    raise ValueError(f"unknown distance {distance!r}; supported: {SUPPORTED}")


def is_similarity(distance: str) -> bool:
    """Similarity functions rank DEscending (reference VPTree 'invert')."""
    return distance in ("cosinesimilarity", "dot")


@jax.jit
def _sq_euclidean(x, y):
    x2 = jnp.sum(x * x, axis=1)[:, None]
    y2 = jnp.sum(y * y, axis=1)[None, :]
    return jnp.maximum(x2 + y2 - 2.0 * (x @ y.T), 0.0)


def brute_force_knn(points: np.ndarray, queries: np.ndarray, k: int,
                    distance: str = "euclidean"):
    """Exact k-NN of each query against all points — one [q, n] device
    block + top-k. Returns (indices [q, k], distances [q, k])."""
    d = pairwise(jnp.asarray(queries), jnp.asarray(points), distance)
    if is_similarity(distance):
        vals, idx = jax.lax.top_k(d, k)
    else:
        vals, idx = jax.lax.top_k(-d, k)
        vals = -vals
    return np.asarray(idx), np.asarray(vals)
