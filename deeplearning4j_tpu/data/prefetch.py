"""Staged, fully-overlapped input pipeline.

Two stages that compose with data/iterators.AsyncDataSetIterator into the
zero-stall feed the fit loop installs automatically (nn/netbase._run_fit
with async_prefetch=True):

  host ETL (N workers)  ->  device prefetch (1 worker)  ->  fit loop
  ParallelDataSetIterator   DevicePrefetchIterator          _fit_epochs

* `ParallelDataSetIterator` is the DataVec-thread-pool analog (reference:
  AsyncDataSetIterator + DataVec ETL threads feeding the compute loop,
  MultiLayerNetwork.java:1023-1025): N workers pull items from one shared
  base iterator, run the heavy `transform` (record decode, normalization,
  host augmentation), and push into a bounded queue with ordered (default)
  or unordered reassembly.
* `DevicePrefetchIterator` runs `jax.device_put` — committed to the target
  device or to a `NamedSharding` — in a background thread `depth` batches
  ahead, so host->device DMA overlaps the previous step's compute instead
  of sitting on the dispatch critical path. A `placement` callable (e.g.
  a mesh-attached net's MeshPlan.shard_batch — parallel/sharded.py) replaces
  the default device_put; a `transform` (data/transforms.DeviceBatchTransform)
  then runs on the already-device-resident batch. Batches come out marked
  `_pipeline_staged`, which tells the fit loop not to re-apply either.

Every stage reports batches/bytes/stall/depth series into the shared
MetricsRegistry (`input_pipeline_*{stage=...}`), the same place the fit
loop's `fit_data_wait_seconds` lands — a pipeline that still stalls is a
number, not a hunch.

Shutdown contract (shared with AsyncDataSetIterator): exhausting,
breaking out of, or erroring out of an epoch closes that epoch's workers
via the consumer generator's `finally`; `close()`/`with` tears down
anything still live. The conftest thread-leak guard enforces it.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    PIPELINE_THREAD_PREFIX,
    DataSetIterator,
    _close_run,
    _get_abortable,
    _put_abortable,
)
from deeplearning4j_tpu.utils import faultpoints as _faults
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import tracing as _tracing

_DONE = object()  # one per ETL worker: "this worker's stream is finished"


def _stage_instruments(stage: str) -> dict:
    """Per-stage pipeline instruments, resolved once per iterator — hot
    paths touch cached children only (netbase._fit_obs discipline)."""
    reg = _metrics.get_registry()
    batches = reg.counter(
        "input_pipeline_batches_total",
        "batches emitted by each input-pipeline stage", ("stage",))
    nbytes = reg.counter(
        "input_pipeline_bytes_total",
        "host bytes emitted by each input-pipeline stage", ("stage",))
    stall = reg.histogram(
        "input_pipeline_stall_seconds",
        "time an input-pipeline stage spent blocked on its queue "
        "(producer: queue full; consumer: queue empty)",
        ("stage", "side"))
    depth = reg.gauge(
        "input_pipeline_depth",
        "current fill of each input-pipeline stage's queue", ("stage",))
    return {
        "batches": batches.labels(stage),
        "bytes": nbytes.labels(stage),
        "producer_stall": stall.labels(stage, "producer"),
        "consumer_stall": stall.labels(stage, "consumer"),
        "depth": depth.labels(stage),
    }


def _ds_nbytes(ds) -> int:
    """Byte accounting for the stage metrics. Total by design — it runs
    on the worker's post-delivery path, where an exception would kill the
    worker silently; arbitrary non-DataSet ETL items count as 0."""
    if isinstance(ds, MultiDataSet):
        arrays = list(ds.features) + list(ds.labels) \
            + list(ds.features_masks or []) + list(ds.labels_masks or [])
    elif isinstance(ds, DataSet):
        arrays = [ds.features, ds.labels, ds.features_mask, ds.labels_mask]
    else:
        return 0
    return sum(int(getattr(a, "nbytes", 0)) for a in arrays if a is not None)


def _carry_metadata(src, dst):
    """Propagate the bookkeeping attributes a placement/transform must
    not drop: pad-aware example counts (the MeshPlan shard_batch's
    `reported_examples`) and the staged marker. Every stage that rebuilds
    a DataSet routes through here (transforms.py included) so new
    metadata has one place to live."""
    n = getattr(src, "reported_examples", None)
    if n is not None:
        dst.reported_examples = n
    if getattr(src, "_pipeline_staged", False):
        dst._pipeline_staged = True
    return dst


def place_dataset(ds, target):
    """`jax.device_put` every array of a DataSet/MultiDataSet onto
    `target` (a Device or a Sharding) — the default placement stage. A
    batch that already lives there comes back buffer-shared, so
    re-staging pre-placed data is free."""
    import jax

    put = lambda a: None if a is None else jax.device_put(a, target)
    if isinstance(ds, MultiDataSet):
        out = MultiDataSet(
            [put(f) for f in ds.features],
            [put(l) for l in ds.labels],
            None if ds.features_masks is None
            else [put(m) for m in ds.features_masks],
            None if ds.labels_masks is None
            else [put(m) for m in ds.labels_masks],
        )
    else:
        out = DataSet(put(ds.features), put(ds.labels),
                      put(ds.features_mask), put(ds.labels_mask))
    return _carry_metadata(ds, out)


class ParallelDataSetIterator(DataSetIterator):
    """Multi-worker ETL over one splittable base iterator.

    `base` yields work items — already-built DataSets, or raw records
    (paths, encoded rows) that `transform` turns into DataSets. Workers
    share the base through a lock (the pull is cheap; `transform` is the
    expensive part and runs unlocked in parallel), push into a bounded
    queue, and the consumer reassembles:

    * ordered=True (default): batches come out in base order — a reorder
      buffer holds early arrivals, so training curves are independent of
      worker scheduling. An item whose transform raised surfaces at its
      position, after every earlier batch was consumed.
    * ordered=False: completion order, minimum latency.

    Exceptions propagate to the consumer; end-of-stream is reached when
    every worker has drained the base. Shutdown follows the module
    contract (close-on-break, `close()`, `with`).
    """

    def __init__(self, base, transform: Optional[Callable] = None,
                 workers: int = 2, queue_size: Optional[int] = None,
                 ordered: bool = True, stage: str = "etl",
                 health_stall_after: float = 120.0):
        self.base = base
        self.transform = transform
        self.workers = max(1, int(workers))
        self.queue_size = max(self.workers, int(queue_size)
                              if queue_size is not None else 2 * self.workers)
        self.ordered = ordered
        self.stage = stage
        self.health_stall_after = health_stall_after
        self._ins = _stage_instruments(stage)
        self._active: List[tuple] = []

    def __iter__(self):
        src = iter(self.base)
        src_lock = threading.Lock()
        seq_box = [0]
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        stop = threading.Event()
        ins = self._ins
        # span context captured on the CONSUMER thread: workers attach it
        # so anything they record (fault markers, future spans) parents
        # into the trace that is iterating, not a fresh per-worker root
        trace_ctx = _tracing.current_context()
        # ONE heartbeat shared by all workers: each holds a busy slot
        # while it owns an item (base pull + transform); the component
        # stalls when the OLDEST slot goes stale, so one wedged worker
        # is not masked by its siblings (utils/health)
        hb = _health.get_health().register(
            f"pipeline_{self.stage}", stall_after=self.health_stall_after)

        def worker():
            while not stop.is_set():
                seq = None
                try:
                    # busy only INSIDE the lock: a worker queued on
                    # src_lock behind a slow-but-progressing base is
                    # idle, not stalled — only the thread actually
                    # pulling (a wedged base) or transforming owes
                    # progress
                    with src_lock:
                        with hb.busy():
                            try:
                                item = next(src)
                            except StopIteration:
                                return
                            seq = seq_box[0]
                            seq_box[0] += 1
                    with hb.busy():
                        # chaos hook: an `error` fault is a raising ETL
                        # transform (propagates in-position to the
                        # consumer); `hang` is the wedged-worker case
                        # the shared heartbeat's oldest-slot rule
                        # detects
                        _faults.fault_point("etl_worker", stage=self.stage)
                        out = (self.transform(item) if self.transform
                               else item)
                except BaseException as e:
                    # seq None: the BASE iterator raised — deliver
                    # immediately (every worker will hit it; first wins)
                    _put_abortable(q, (-1 if seq is None else seq, e, None),
                                   stop)
                    return
                # the put is NOT busy time: a full queue means the
                # consumer is slow, which is the consumer's stall to own
                t0 = time.perf_counter()
                if not _put_abortable(q, (seq, None, out), stop):
                    return
                ins["producer_stall"].observe(time.perf_counter() - t0)
                ins["batches"].inc()
                ins["bytes"].inc(_ds_nbytes(out))

        def worker_main():
            _tracing.attach(trace_ctx)  # thread-local; dies with the thread
            try:
                worker()
            finally:
                # the _DONE marker must go out even on an unexpected
                # failure — a missing marker would hang the consumer
                _put_abortable(q, _DONE, stop)

        threads = []
        for i in range(self.workers):
            t = threading.Thread(
                target=worker_main, daemon=True,
                name=f"{PIPELINE_THREAD_PREFIX}-etl-{i}")
            threads.append(t)
        run = (q, stop, threads)
        self._active.append(run)
        ins["depth"].set_function(q.qsize)
        for t in threads:
            t.start()
        try:
            yield from self._reassemble(q, stop, ins)
        finally:
            _close_run(q, stop, threads)
            _health.get_health().unregister(hb)
            if run in self._active:
                self._active.remove(run)

    def _reassemble(self, q, stop, ins):
        done, buf, nxt = 0, {}, 0
        while done < self.workers:
            t0 = time.perf_counter()
            item = _get_abortable(q, stop)
            ins["consumer_stall"].observe(time.perf_counter() - t0)
            if item is None:  # aborted by an external close()
                return
            if item is _DONE:
                done += 1
                continue
            seq, err, out = item
            if not self.ordered:
                if err is not None:
                    raise err
                yield out
                continue
            if seq < 0:  # base-iterator failure: position unknowable
                raise err
            buf[seq] = (err, out)
            while nxt in buf:
                e, o = buf.pop(nxt)
                nxt += 1
                if e is not None:
                    raise e
                yield o
        # every worker put its items before its _DONE marker (per-producer
        # FIFO), so whatever remains buffered is complete — flush in order
        for seq in sorted(buf):
            e, o = buf[seq]
            if e is not None:
                raise e
            yield o

    def close(self):
        for q, stop, threads in list(self._active):
            _close_run(q, stop, threads)
        self._active.clear()

    def reset(self):
        self.close()
        if hasattr(self.base, "reset"):
            self.base.reset()

    def batch_size(self):
        bs = getattr(self.base, "batch_size", None)
        return bs() if callable(bs) else None

    def total_examples(self):
        te = getattr(self.base, "total_examples", None)
        return te() if callable(te) else None

    # the resume protocol delegates to the base: ETL workers hold no
    # replayable position (in-flight batches are re-derived from the
    # base's epoch state, data/iterators.DataSetIterator.state)
    def state(self):
        st = getattr(self.base, "state", None)
        return st() if callable(st) else None

    def restore_state(self, state):
        rs = getattr(self.base, "restore_state", None)
        if callable(rs):
            rs(state)


class DevicePrefetchIterator(DataSetIterator):
    """Device-resident double-buffered prefetch: a background thread
    stages each host batch onto the accelerator `depth` batches ahead of
    the fit loop, so host->device DMA (and, under ParallelWrapper, the
    per-device shard split) overlaps the previous step's compute.

    placement:
      * None — `jax.device_put` committed to `device` (default: the
        process default device) or to a NamedSharding passed as `device`.
      * a callable ds->ds — a custom staging function; a mesh-attached
        net (set_mesh) installs its MeshPlan's `shard_batch` here, which
        is how the per-shard batch split leaves the dispatch critical
        path. shard_batch passes through arrays already committed with
        the mesh sharding (zero-copy), so pre-staged batches are never
        transferred twice.
    transform: an optional on-device batch transform (ds->ds, e.g.
      data/transforms.DeviceBatchTransform) applied AFTER placement — the
      per-pixel work runs as a jitted program on the accelerator, not in
      host numpy.

    Emitted batches carry `_pipeline_staged=True`: nn/netbase's fit loop
    skips its own `_batch_transform`/input-transform application for
    them, so a pre-placed batch is never transferred (or augmented)
    twice. Device memory bound: `depth + 1` staged batches in flight.
    """

    def __init__(self, base: DataSetIterator, depth: int = 2,
                 placement=None, device=None,
                 transform: Optional[Callable] = None,
                 close_base: bool = False,
                 stage: str = "device_prefetch",
                 health_stall_after: float = 120.0):
        self.base = base
        self.depth = max(1, int(depth))
        self.placement = placement
        self.device = device
        self.transform = transform
        self.close_base = close_base
        self.stage = stage
        self.health_stall_after = health_stall_after
        self._ins = _stage_instruments(stage)
        self._active: List[tuple] = []
        self._sentinel = object()

    def _resolve_target(self):
        """Default staging target, resolved on the CONSUMER thread at
        epoch start (not in the worker): `jax.default_device` is a
        thread-local config override, so only the fit thread sees the
        user's `with jax.default_device(d):` scope."""
        if callable(self.placement) or self.device is not None:
            return self.device
        import jax

        return (getattr(jax.config, "jax_default_device", None)
                or jax.devices()[0])

    def _stage(self, ds, target):
        if getattr(ds, "_pipeline_staged", False):
            return ds  # already staged upstream (e.g. a nested pipeline)
        # chaos hook: an `error` fault is a failed host->device transfer
        # (surfaces in the consumer, fit fails loudly); `hang` is a
        # device_put that never returns — the stale busy slot the
        # prefetch heartbeat exists to catch
        _faults.fault_point("device_put", stage=self.stage)
        if callable(self.placement):
            out = _carry_metadata(ds, self.placement(ds))
        else:
            out = place_dataset(ds, target)
        if self.transform is not None:
            out = _carry_metadata(out, self.transform(out))
        out._pipeline_staged = True
        return out

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        err: List[BaseException] = []
        ins = self._ins
        sentinel = self._sentinel
        target = self._resolve_target()
        # consumer-thread span context, attached by the worker below: the
        # prefetch handoff keeps parentage — staging spans land in the
        # iterating trace instead of silently starting new roots
        trace_ctx = _tracing.current_context()

        # liveness: busy while an item is in hand (base pull + staging —
        # a wedged upstream iterator or a device_put that never returns
        # goes stale); the backpressured put stays outside busy (a full
        # queue is the fit loop's slowness, tracked by ITS heartbeat)
        hb = _health.get_health().register(
            self.stage, stall_after=self.health_stall_after)

        def worker():
            _tracing.attach(trace_ctx)  # thread-local; dies with the thread
            try:
                it = iter(self.base)
                while True:
                    with hb.busy():
                        try:
                            ds = next(it)
                        except StopIteration:
                            return
                        nb = _ds_nbytes(ds)  # host bytes, before staging
                        with _tracing.span("prefetch/stage",
                                           stage=self.stage):
                            staged = self._stage(ds, target)
                    t0 = time.perf_counter()
                    if not _put_abortable(q, staged, stop):
                        return
                    ins["producer_stall"].observe(time.perf_counter() - t0)
                    ins["batches"].inc()
                    ins["bytes"].inc(nb)
            except BaseException as e:
                err.append(e)
            finally:
                _put_abortable(q, sentinel, stop)

        t = threading.Thread(target=worker, daemon=True,
                             name=f"{PIPELINE_THREAD_PREFIX}-device-prefetch")
        run = (q, stop, [t])
        self._active.append(run)
        ins["depth"].set_function(q.qsize)
        t.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = _get_abortable(q, stop)
                ins["consumer_stall"].observe(time.perf_counter() - t0)
                if item is None or item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            _close_run(q, stop, [t])
            _health.get_health().unregister(hb)
            if run in self._active:
                self._active.remove(run)

    def close(self):
        for q, stop, threads in list(self._active):
            _close_run(q, stop, threads)
        self._active.clear()
        if self.close_base:
            self.base.close()

    def reset(self):
        self.base.reset()

    def batch_size(self):
        return self.base.batch_size()

    def total_examples(self):
        return self.base.total_examples()

    def state(self):
        st = getattr(self.base, "state", None)
        return st() if callable(st) else None

    def restore_state(self, state):
        rs = getattr(self.base, "restore_state", None)
        if callable(rs):
            rs(state)
