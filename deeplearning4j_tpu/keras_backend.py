"""Keras-backend entry point (reference: deeplearning4j-keras, 452 LoC —
a py4j GatewayServer exposing DeepLearning4jEntryPoint.fit(), with batch
data handed over as HDF5 files; DeepLearning4jEntryPoint.java:22-41).

TPU-native shape: the frontend language IS Python here, so the gateway
degenerates to (a) a direct function — fit_from_keras_config — and (b) an
HTTP entry point for out-of-process frontends, accepting the same payload
the reference took over py4j: a Keras 1.x model-config JSON plus feature/
label arrays (npy paths or HDF5 datasets)."""

from __future__ import annotations

import json
import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu.utils.jsonhttp import JsonHttpServer, json_response

from deeplearning4j_tpu.modelimport.keras import (
    import_keras_sequential_config,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _load_array(path: str, dataset: Optional[str] = None) -> np.ndarray:
    if path.endswith((".h5", ".hdf5")):
        import h5py

        with h5py.File(path, "r") as f:
            return np.asarray(f[dataset or "data"])
    return np.load(path)


def fit_from_keras_config(model_config_json: str,
                          features: np.ndarray, labels: np.ndarray,
                          *, training_config_json: Optional[str] = None,
                          batch_size: int = 32, nb_epoch: int = 1,
                          precision: str = "f32"):
    """The EntryPoint.fit analog: build the network from a Keras 1.x
    Sequential config, train, return (net, final_score). Without a
    training_config the loss defaults to categorical crossentropy (the
    reference's entry point always receives a compiled model; a bare
    architecture still has to train here)."""
    if training_config_json is None:
        training_config_json = json.dumps(
            {"loss": "categorical_crossentropy"})
    conf, _ = import_keras_sequential_config(
        model_config_json, training_config_json, precision=precision)
    net = MultiLayerNetwork(conf).init()
    net.fit(np.asarray(features), np.asarray(labels),
            batch_size=batch_size, epochs=nb_epoch)
    return net, float(np.asarray(net._score))


class KerasBackendServer:
    """POST /fit
    {"model_config": "<keras json>", "features_path": ..., "labels_path":
     ..., "batch_size": 32, "nb_epoch": 1} -> {"score": float}
    The model is retained; POST /evaluate {"features_path", "labels_path"}
    scores it."""

    def __init__(self, port: int = 0):
        self._server = JsonHttpServer(post=self._post, port=port)
        self._net: Optional[MultiLayerNetwork] = None
        self._lock = threading.Lock()

    @property
    def port(self) -> int:
        return self._server.port

    def _post(self, path, body, headers):
        req = json.loads(body)
        if path == "/fit":
            return json_response(self._fit(req))
        if path == "/evaluate":
            return json_response(self._evaluate(req))
        return None

    def _fit(self, body: dict) -> dict:
        x = _load_array(body["features_path"], body.get("features_dataset"))
        y = _load_array(body["labels_path"], body.get("labels_dataset"))
        with self._lock:
            net, score = fit_from_keras_config(
                body["model_config"], x, y,
                training_config_json=body.get("training_config"),
                batch_size=int(body.get("batch_size", 32)),
                nb_epoch=int(body.get("nb_epoch", 1)))
            self._net = net
        return {"score": score}

    def _evaluate(self, body: dict) -> dict:
        if self._net is None:
            raise ValueError("no model fitted yet")
        x = _load_array(body["features_path"], body.get("features_dataset"))
        y = _load_array(body["labels_path"], body.get("labels_dataset"))
        with self._lock:
            ev = self._net.evaluate(
                self._make_iter(x, y, int(body.get("batch_size", 128))))
        return {"accuracy": ev.accuracy(), "f1": ev.f1()}

    @staticmethod
    def _make_iter(x, y, batch):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator

        return ListDataSetIterator(DataSet(x, y), batch)

    def start(self) -> int:
        return self._server.start()

    def stop(self):
        self._server.stop()
