"""Word2Vec facade.

Analog of the reference's models/word2vec/Word2Vec.java:32 (extends
SequenceVectors) + Word2Vec.Builder: tokenize a sentence stream with a
TokenizerFactory and train word embeddings. Defaults follow the
reference: hierarchical softmax on, negative sampling off, skip-gram.
"""

from __future__ import annotations

from typing import Iterable, Optional

from deeplearning4j_tpu.nlp.sequencevectors import (
    SequenceVectors,
    VectorsConfiguration,
)
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)


class Word2Vec(SequenceVectors):
    def __init__(self, conf: VectorsConfiguration,
                 sentences: Optional[Iterable[str]] = None,
                 tokenizer: Optional[TokenizerFactory] = None):
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        seqs = None
        if sentences is not None:
            seqs = [self.tokenizer.create(s).get_tokens() for s in sentences]
        super().__init__(conf, seqs)

    class Builder:
        """Fluent builder (reference: Word2Vec.Builder)."""

        def __init__(self):
            self._conf = VectorsConfiguration()
            self._sentences = None
            self._tokenizer = None

        def min_word_frequency(self, n: int):
            self._conf.min_word_frequency = int(n)
            return self

        def layer_size(self, n: int):
            self._conf.layer_size = int(n)
            return self

        def window_size(self, n: int):
            self._conf.window = int(n)
            return self

        def iterations(self, n: int):
            self._conf.iterations = int(n)
            return self

        def epochs(self, n: int):
            self._conf.epochs = int(n)
            return self

        def learning_rate(self, lr: float):
            self._conf.learning_rate = float(lr)
            return self

        def min_learning_rate(self, lr: float):
            self._conf.min_learning_rate = float(lr)
            return self

        def negative_sample(self, k: int):
            self._conf.negative = int(k)
            return self

        def use_hierarchic_softmax(self, flag: bool):
            self._conf.use_hierarchic_softmax = bool(flag)
            return self

        def sampling(self, t: float):
            self._conf.sampling = float(t)
            return self

        def batch_size(self, n: int):
            self._conf.batch_size = int(n)
            return self

        def seed(self, s: int):
            self._conf.seed = int(s)
            return self

        def elements_learning_algorithm(self, name: str):
            self._conf.elements_learning_algorithm = name
            return self

        def iterate(self, sentences: Iterable[str]):
            self._sentences = sentences
            return self

        def tokenizer_factory(self, tf: TokenizerFactory):
            self._tokenizer = tf
            return self

        def build(self) -> "Word2Vec":
            return Word2Vec(self._conf, self._sentences, self._tokenizer)
