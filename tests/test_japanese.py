"""Japanese lattice segmenter (nlp/japanese.py) — morphological
segmentation on the TokenizerFactory SPI (the deeplearning4j-nlp-japanese
slot; reference bundles a Kuromoji fork, SURVEY aux: CJK tokenization)."""

from deeplearning4j_tpu.nlp.japanese import (
    JapaneseTokenizerFactory,
    segment,
)
from deeplearning4j_tpu.nlp.tokenization import CJKTokenizerFactory


def test_particles_and_dictionary_words_recovered():
    assert segment("私は東京に行きます") == \
        ["私", "は", "東京", "に", "行き", "ます"]
    assert segment("猫が水を飲んだ") == ["猫", "が", "水", "を", "飲んだ"]
    assert segment("今日はとても暑いですね") == \
        ["今日", "は", "とても", "暑い", "です", "ね"]


def test_punctuation_and_whitespace_are_boundaries():
    toks = segment("明日、学校で勉強します。")
    assert toks == ["明日", "学校", "で", "勉強", "します"]


def test_unknown_runs_stay_whole_by_class():
    # katakana loanword + latin word are not in the lexicon: whole runs
    toks = segment("カタカナとAlphabetと漢字")
    assert "カタカナ" in toks and "Alphabet" in toks and "漢字" in toks


def test_unknown_kanji_compound_does_not_swallow_particles():
    # 量子力学 is out-of-lexicon; は/の must still split off
    toks = segment("量子力学の本は難しい")
    assert "の" in toks and "は" in toks and "難しい" in toks
    assert "量子力学" in toks


def test_factory_spi_and_custom_lexicon():
    f = JapaneseTokenizerFactory(lexicon={"量子力学": 3.0})
    toks = f.create("量子力学は難しい").get_tokens()
    assert toks == ["量子力学", "は", "難しい"]


def test_beats_bigram_fallback_on_word_boundaries():
    """The lattice recovers real word units where the bigram fallback
    emits overlapping han pairs that cross word boundaries."""
    text = "東京大学の学生"
    lattice = segment(text)
    bigrams = CJKTokenizerFactory().create(text).get_tokens()
    assert "東京" in lattice and "学生" in lattice
    assert "京大" in bigrams       # boundary-crossing bigram artifact
    assert "京大" not in lattice   # the lattice never crosses 東京|大学


def test_unknown_hiragana_run_does_not_swallow_particle():
    # out-of-lexicon hiragana word + particle: the prefix unknown-edges
    # must expose the が boundary instead of fusing ぬるぽが
    toks = segment("ぬるぽが好き")
    assert toks[:2] == ["ぬるぽ", "が"]
    assert "好き" in toks


def test_empty_and_nonjapanese():
    assert segment("") == []
    assert segment("hello world") == ["hello", "world"]
