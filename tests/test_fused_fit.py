"""Multi-batch fused fit equivalence (NetworkBase.set_fused_steps): K
minibatches per jitted dispatch must produce the SAME trajectory —
params, updater state, iteration count — as the per-batch loop, for
MultiLayerNetwork (standard + cross-batch TBPTT programs) and
ComputationGraph. Ragged tails and mid-stream shape changes must fall
back to per-batch fits, not crash or skip data.

This is the dispatch-latency amortizer playing the reference's
AsyncDataSetIterator throughput role (MultiLayerNetwork.java:1023-1025)
at the XLA level."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.compgraph import ComputationGraph
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    InputType,
    LSTM,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.network import BackpropType
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _max_tree_diff(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return max(
        (float(jnp.max(jnp.abs(jnp.asarray(x, jnp.float32)
                               - jnp.asarray(y, jnp.float32))))
         for x, y in zip(la, lb)),
        default=0.0,
    )


def _mlp_conf(dropout=0.0):
    return (
        NeuralNetConfiguration.builder()
        .seed(11)
        .updater("adam")
        .learning_rate(0.01)
        .list()
        .layer(DenseLayer(n_out=16, activation="relu", dropout=dropout))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(8))
        .build()
    )


def _cls_data(n=96, nin=8, k=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, nin)).astype(np.float32)
    y = np.zeros((n, k), np.float32)
    y[np.arange(n), rng.integers(0, k, n)] = 1.0
    return x, y


def _pair(conf_fn, fused_k):
    a = MultiLayerNetwork(conf_fn()).init()
    b = MultiLayerNetwork(conf_fn()).init().set_fused_steps(fused_k)
    return a, b


def test_fused_std_matches_loop_exact_chunks():
    x, y = _cls_data(96)  # batch 24 -> 4 batches: one K=4 chunk per epoch
    loop, fused = _pair(_mlp_conf, 4)
    for net in (loop, fused):
        net.fit(x, y, epochs=3, batch_size=24, async_prefetch=False)
    assert fused.iteration == loop.iteration == 12
    assert _max_tree_diff(loop.params_list, fused.params_list) < 1e-6
    assert _max_tree_diff(loop.upd_state, fused.upd_state) < 1e-6
    assert abs(float(loop._score) - float(fused._score)) < 1e-6


def test_fused_std_ragged_tail_falls_back():
    # 96 examples / batch 20 -> 4 full batches (one fused K=4 chunk) + 1
    # ragged batch of 16 whose signature break sends it down the per-step
    # path: same trajectory as the loop, nothing dropped.
    x, y = _cls_data(96)
    loop, fused = _pair(_mlp_conf, 4)
    for net in (loop, fused):
        net.fit(x, y, epochs=2, batch_size=20, async_prefetch=False)
    assert fused.iteration == loop.iteration == 10
    assert _max_tree_diff(loop.params_list, fused.params_list) < 1e-6


def test_fused_std_dropout_rng_matches():
    x, y = _cls_data(96)
    loop, fused = _pair(lambda: _mlp_conf(dropout=0.5), 4)
    for net in (loop, fused):
        net.fit(x, y, epochs=2, batch_size=24, async_prefetch=False)
    assert _max_tree_diff(loop.params_list, fused.params_list) < 1e-6


def test_fused_chunk_smaller_than_k_falls_back():
    x, y = _cls_data(48)  # 2 batches of 24 < K=8 -> per-step path
    loop, fused = _pair(_mlp_conf, 8)
    for net in (loop, fused):
        net.fit(x, y, epochs=2, batch_size=24, async_prefetch=False)
    assert fused.iteration == loop.iteration == 4
    assert _max_tree_diff(loop.params_list, fused.params_list) < 1e-6


def _rnn_conf():
    return (
        NeuralNetConfiguration.builder()
        .seed(5)
        .updater("adam")
        .learning_rate(0.02)
        .list()
        .layer(LSTM(n_out=8, activation="tanh"))
        .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(3))
        .backprop_type(BackpropType.TRUNCATED_BPTT)
        .t_bptt_lengths(4)
        .build()
    )


def test_fused_tbptt_cross_batch_matches_loop():
    rng = np.random.default_rng(2)
    n, t = 64, 12  # batch 16 -> 4 fit batches x 3 segments each
    x = rng.normal(size=(n, t, 3)).astype(np.float32)
    cs = np.cumsum(x[..., 0], axis=1)
    y = np.zeros((n, t, 2), np.float32)
    y[..., 0] = (cs <= 0).astype(np.float32)
    y[..., 1] = (cs > 0).astype(np.float32)

    loop = MultiLayerNetwork(_rnn_conf()).init()
    fused = MultiLayerNetwork(_rnn_conf()).init().set_fused_steps(2)
    for net in (loop, fused):
        net.fit(x, y, epochs=2, batch_size=16, async_prefetch=False)
    # 2 epochs x 4 batches x 3 segments
    assert fused.iteration == loop.iteration == 24
    assert _max_tree_diff(loop.params_list, fused.params_list) < 1e-6
    assert _max_tree_diff(loop.upd_state, fused.upd_state) < 1e-6
    assert abs(float(loop._score) - float(fused._score)) < 1e-6


def _graph_conf():
    return (
        NeuralNetConfiguration.builder()
        .seed(3)
        .updater("adam")
        .learning_rate(0.01)
        .graph_builder()
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_out=16, activation="relu"), "in")
        .add_layer("out",
                   OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"), "d")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(8))
        .build()
    )


def test_fused_graph_matches_loop():
    x, y = _cls_data(96)
    loop = ComputationGraph(_graph_conf()).init()
    fused = ComputationGraph(_graph_conf()).init().set_fused_steps(4)
    for net in (loop, fused):
        net.fit(x, y, epochs=3, batch_size=24, async_prefetch=False)
    assert fused.iteration == loop.iteration == 12
    assert _max_tree_diff(loop.params_list, fused.params_list) < 1e-6
    assert _max_tree_diff(loop.upd_state, fused.upd_state) < 1e-6


def _rnn_graph_conf(fwd=4, bwd=4):
    return (
        NeuralNetConfiguration.builder().seed(5)
        .updater("adam").learning_rate(0.02)
        .graph_builder().add_inputs("seq")
        .add_layer("lstm", LSTM(n_out=8, activation="tanh"), "seq")
        .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax",
                                         loss="mcxent"), "lstm")
        .set_outputs("out")
        .set_input_types(InputType.recurrent(3))
        .backprop_type("tbptt")
        .t_bptt_lengths(fwd, bwd)
        .build()
    )


def _seq_xy(n=32, t=12, seed=2):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, 3)).astype(np.float32)
    cs = np.cumsum(x[..., 0], axis=1)
    y = np.zeros((n, t, 2), np.float32)
    y[..., 0] = (cs <= 0).astype(np.float32)
    y[..., 1] = (cs > 0).astype(np.float32)
    return x, y


class _NoOp:
    def iteration_done(self, model, iteration, info):
        pass

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass


@pytest.mark.parametrize("fwd,bwd", [(4, 4), (6, 3)])
def test_graph_tbptt_fused_matches_loop(fwd, bwd):
    """CG fused-TBPTT (all segments one dispatch) == per-segment loop,
    incl. the bwd<fwd truncated builder — the ComputationGraph twin of
    tests/test_tbptt_fused.py (a listener forces the loop path)."""
    x, y = _seq_xy(t=12)
    loop = ComputationGraph(_rnn_graph_conf(fwd, bwd)).init()
    loop.add_listener(_NoOp())
    fused = ComputationGraph(_rnn_graph_conf(fwd, bwd)).init()
    for net in (loop, fused):
        net.fit(x, y, epochs=2, batch_size=16, async_prefetch=False)
    assert fused.iteration == loop.iteration
    assert _max_tree_diff(loop.params_list, fused.params_list) < 1e-6
    assert _max_tree_diff(loop.upd_state, fused.upd_state) < 1e-6
    assert abs(float(loop._score) - float(fused._score)) < 1e-6


def test_graph_tbptt_ragged_tail_falls_back():
    x, y = _seq_xy(t=10)  # 10 % 4 != 0 -> loop path on both
    loop = ComputationGraph(_rnn_graph_conf(4, 4)).init()
    loop.add_listener(_NoOp())
    fused = ComputationGraph(_rnn_graph_conf(4, 4)).init()
    for net in (loop, fused):
        net.fit(x, y, epochs=1, batch_size=16, async_prefetch=False)
    assert fused.iteration == loop.iteration == 2 * 3
    assert _max_tree_diff(loop.params_list, fused.params_list) < 1e-6


def test_fused_listeners_disable_fusion():
    from deeplearning4j_tpu.train.listeners import CollectScoresIterationListener

    x, y = _cls_data(96)
    net = MultiLayerNetwork(_mlp_conf()).init().set_fused_steps(4)
    collector = CollectScoresIterationListener()
    net.add_listener(collector)
    net.fit(x, y, epochs=1, batch_size=24, async_prefetch=False)
    # listeners force the per-step path: one callback per iteration
    assert len(collector.scores) == 4
