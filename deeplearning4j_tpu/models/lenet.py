"""LeNet for MNIST — the minimum end-to-end workload (SURVEY.md §7 stage 6;
reference workload: BASELINE.md "LeNet MNIST MultiLayerNetwork", the
dl4j-examples LenetMnistExample architecture: conv5x5x20 - maxpool2 -
conv5x5x50 - maxpool2 - dense500 relu - softmax10)."""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import (
    ConvolutionLayer,
    DenseLayer,
    InputType,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OutputLayer,
    SubsamplingLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def lenet_conf(seed: int = 123, learning_rate: float = 0.01,
               precision: str = "f32") -> MultiLayerConfiguration:
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Updater.NESTEROVS)
        .learning_rate(learning_rate)
        .momentum(0.9)
        .weight_init("xavier")
        .precision(precision)
        .list()
        .layer(ConvolutionLayer(kernel_size=(5, 5), stride=(1, 1), n_out=20,
                                activation="identity"))
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(ConvolutionLayer(kernel_size=(5, 5), stride=(1, 1), n_out=50,
                                activation="identity"))
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=500, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.convolutional_flat(28, 28, 1))
        .build()
    )


def lenet_network(seed: int = 123, learning_rate: float = 0.01,
                  precision: str = "f32") -> MultiLayerNetwork:
    return MultiLayerNetwork(lenet_conf(seed, learning_rate, precision)).init()
