"""REST model-inference server — the serving front-end the reference left
to users (ParallelInference.java was always embedded behind someone's
HTTP layer; here the layer ships with the framework, sibling of
serving/knnserver.py on the same utils/jsonhttp scaffold).

Wraps a MultiLayerNetwork or ComputationGraph in a bucketed, pipelined
ParallelInference (parallel/inference.py — BATCHED mode fuses concurrent
requests, pads each fused group to a fixed bucket so only ~log2(B)
forward traces ever compile, and overlaps host batch assembly with
device execution). Routes:

    POST /predict  {"features": [[...], ...], "deadline_ms": 250}
                                                -> {"predictions": [...]}
                   (a single flat example is also accepted and returns a
                    single prediction row; a multi-output graph returns
                    one predictions entry per output head; `deadline_ms`
                    — or an X-Deadline-Ms header — is the request's
                    latency budget: work that cannot make it is SHED
                    with 429 + Retry-After, never served late. 503 is
                    reserved for /health degradation.)
    GET  /health   -> {"status": "ok", "model": ..., "feature_shape": ...}
    GET  /metrics  -> {"requests", "examples", "batches", "queue_depth",
                       "buckets", "bucket_hits", "oversized",
                       "forward_compiles", "latency_ms":
                       {"count", "mean_ms", "p50_ms", "p99_ms",
                        "exemplars": [{"le_ms", "value_ms",
                                       "trace_id"}, ...]},
                       ...}
                   (exemplars link latency-bucket maxima to trace ids
                    when tracing is on — resolve one with `cli trace`)
    GET  /metrics?format=prometheus
                   -> text exposition of the process-global registry
                      (utils/metrics.py): serving series plus any
                      training-side fit_step_* / compile_total /
                      helper_* counters living in the same process
    GET  /trace    -> recent host spans as JSONL (utils/tracing.py);
                      ?format=chrome returns a chrome://tracing document
    GET  /alerts   -> live SLO rule states from the attached run ledger
                      (utils/runledger + analysis/slo): per-rule
                      pending/firing lifecycle, recent transitions —
                      machine-readable verdicts, not just gauges
                      (start with --ledger or run_ledger=)
    GET  /tenants  -> the chip-budget view (utils/resourcemeter):
                      per-tenant spend (device-seconds by tier, wire
                      bytes, tokens, HBM), merged admission books,
                      conservation verdicts, firing per-tenant SLO
                      rules. Requests name their tenant via a JSON
                      "tenant" field or the X-Tenant header (field
                      wins, case-insensitive — the deadline contract's
                      shape); spend metering arms with --meter.

Knobs (constructor and CLI flags): `max_batch_size`, `batch_timeout_ms`,
`buckets`, `warmup_shape` (precompiles every bucket before the port
opens, so first requests never pay a compile).
"""

from __future__ import annotations

import argparse
import json
import logging
import math
import time
import urllib.parse
from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.parallel.inference import (
    DeadlineExceeded,
    InferenceMode,
    ParallelInference,
    ReplicaPool,
    RequestRejected,
    RequestValidationError,
)
from deeplearning4j_tpu.serving.decode import DecodeEngine
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import resourcemeter as _resourcemeter
from deeplearning4j_tpu.utils import runledger as _runledger
from deeplearning4j_tpu.utils import tenancy as _tenancy
from deeplearning4j_tpu.utils import tracing as _tracing
from deeplearning4j_tpu.utils.jsonhttp import JsonHttpServer, json_response
from deeplearning4j_tpu.utils.latency import LatencyTracker

logger = logging.getLogger("deeplearning4j_tpu")


class InferenceServer:
    def __init__(
        self,
        model,
        port: int = 0,
        mesh=None,
        inference_mode: str = InferenceMode.BATCHED,
        max_batch_size: int = 64,
        batch_timeout_ms: float = 2.0,
        buckets: Optional[Sequence[int]] = None,
        warmup_shape: Optional[Sequence[int]] = None,
        health_stall_after: float = 30.0,
        n_replicas: int = 1,
        queue_capacity: int = 1024,
        default_deadline_ms: Optional[float] = None,
        request_timeout: float = 30.0,
        run_ledger=None,
        decode_slots: int = 0,
        decode_eos_token: Optional[int] = None,
        decode_max_tokens: int = 64,
        decode_tenant_weights: Optional[dict] = None,
        decode_queue_capacity: int = 256,
    ):
        # n_replicas >= 2 turns on the self-healing pool: each replica's
        # collector/dispatcher heartbeats are watched separately, an
        # unhealthy replica is evicted (only its in-flight requests fail;
        # queued work re-routes to a sibling with no user-visible error)
        # and respawned — the eviction/respawn cycle shows up in
        # component_health transitions and serving_replica_* counters on
        # the same /metrics scrape as the traffic series
        if int(n_replicas) > 1:
            self.inference = ReplicaPool(
                model, n_replicas=int(n_replicas), mesh=mesh,
                inference_mode=inference_mode,
                max_batch_size=max_batch_size,
                batch_timeout_ms=batch_timeout_ms, buckets=buckets,
                health_stall_after=health_stall_after,
                queue_capacity=queue_capacity,
                default_deadline_ms=default_deadline_ms,
            )
        else:
            self.inference = ParallelInference(
                model, mesh, inference_mode, max_batch_size,
                batch_timeout_ms, buckets,
                health_stall_after=health_stall_after,
                queue_capacity=queue_capacity,
                default_deadline_ms=default_deadline_ms,
            )
        if warmup_shape is not None:
            self.inference.warmup(warmup_shape)
        # the autoregressive tier: decode_slots > 0 mounts a continuous-
        # batching DecodeEngine (serving/decode.py) over the SAME model
        # and exposes POST /generate behind the same deadline/429
        # contract as /predict (streaming via chunked ndjson)
        self.decode = None
        if int(decode_slots) > 0:
            self.decode = DecodeEngine(
                model, n_slots=int(decode_slots),
                eos_token=decode_eos_token,
                default_max_tokens=int(decode_max_tokens),
                default_deadline_ms=default_deadline_ms,
                tenant_weights=decode_tenant_weights,
                queue_capacity=int(decode_queue_capacity),
            )
        # run-ledger opt-in at the server level (works for both the
        # single-PI and ReplicaPool modes): a path builds a RunLedger
        # with the default rule pack derived from THIS server's config
        # (the p99 deadline burn objective, queue boundedness) and
        # closes it on stop(); an instance is attached as given.
        self._owned_ledger = self._attached_ledger = None
        if run_ledger is not None:
            if isinstance(run_ledger, str):
                from deeplearning4j_tpu.analysis.slo import default_rule_pack

                self._owned_ledger = _runledger.RunLedger(
                    run_ledger,
                    rules=default_rule_pack(serving={
                        "default_deadline_ms": default_deadline_ms,
                        "queue_capacity": queue_capacity,
                    }))
                self._attached_ledger = _runledger.attach(
                    self._owned_ledger)
            else:
                self._attached_ledger = _runledger.attach(run_ledger)
        self.latency = LatencyTracker()
        # request latency also lands in the shared registry so one
        # Prometheus scrape carries serving AND training series
        self._m_latency = _metrics.get_registry().histogram(
            "serving_request_seconds",
            "end-to-end /predict latency (admission to result)").labels()
        self._server = JsonHttpServer(get=self._get, post=self._post,
                                      port=port,
                                      request_timeout=request_timeout)

    @property
    def port(self) -> int:
        return self._server.port

    def metrics(self) -> dict:
        m = self.inference.metrics()
        # JSON object keys must be strings; bucket sizes are ints
        m["bucket_hits"] = {str(k): v for k, v in m["bucket_hits"].items()}
        m["latency_ms"] = self.latency.snapshot()
        # per-bucket latency exemplars (value + trace_id) from the cached
        # serving_request_seconds child: the scrape-to-trace link —
        # resolve one with `cli trace http://host:port --trace-id <id>`.
        # Converted to ms-suffixed keys: everything else in this
        # latency_ms object is milliseconds, and a seconds-valued field
        # next to p99_ms is a silent 1000x misread
        m["latency_ms"]["exemplars"] = [
            {"le_ms": (e["le"] if isinstance(e["le"], str)
                       else round(e["le"] * 1e3, 6)),
             "value_ms": round(e["value"] * 1e3, 6),
             "trace_id": e["trace_id"], "ts": e["ts"],
             **({"tenant": e["tenant"]} if "tenant" in e else {})}
            for e in self._m_latency.exemplars()]
        if self.decode is not None:
            # the autoregressive tier's books on the same scrape: slot
            # occupancy, per-tenant conservation, token counts, version
            m["decode"] = self.decode.metrics()
        return m

    # -- request handling ----------------------------------------------------

    def _get(self, path, body, headers):
        parsed = urllib.parse.urlparse(path)
        route = parsed.path
        query = urllib.parse.parse_qs(parsed.query)
        fmt = (query.get("format") or [""])[0]
        if route == "/health":
            # the aggregated health model (utils/health): worst component
            # status, with per-component stall detail. 503 when UNHEALTHY
            # so load balancers stop routing here (the replica-eviction
            # hook); degraded stays 200 — shedding, not eviction.
            shape = self.inference._expected_shape
            h = _health.get_health().status()
            code = 503 if h["status"] == _health.UNHEALTHY else 200
            return json_response({
                "status": h["status"],
                "components": h["components"],
                "model": type(self.inference.model).__name__,
                "feature_shape": None if shape is None else list(shape),
            }, code)
        if route == "/metrics":
            if fmt == "prometheus":
                text = _metrics.get_registry().to_prometheus()
                return 200, "text/plain; version=0.0.4", text.encode()
            if fmt == "registry":
                # the registry's JSON snapshot (same series as the
                # prometheus exposition, machine-readable) — what
                # `cli metrics --watch --url` diffs per tick
                return json_response(_metrics.get_registry().snapshot())
            return json_response(self.metrics())
        if route == "/alerts":
            # the live SLO verdicts (analysis/slo evaluated on the run
            # ledger's recorder thread): per-rule pending/firing state,
            # recent lifecycle transitions, and which rules fire right
            # now — machine-readable, the scrape a soak gate or the
            # autotune controller polls instead of eyeballing gauges
            # THIS server's ledger first: another component attaching/
            # detaching the process-global slot (a fit's scoped ledger
            # ending mid-serve) must not hijack or blank this endpoint
            led = (self._owned_ledger or self._attached_ledger
                   or _runledger.current())
            if led is None:
                return json_response({
                    "ledger": None, "rules": [], "firing": [],
                    "transitions": [],
                    "note": "no run ledger attached (start the server "
                            "with run_ledger=, or attach one via "
                            "utils.runledger)"})
            return json_response(led.alert_status())
        if route == "/trace":
            # recent host spans — JSONL by default (tail-able), or the
            # chrome://tracing document with ?format=chrome
            tracer = _tracing.get_tracer()
            if fmt == "chrome":
                return json_response(tracer.to_chrome_trace())
            n_raw = (query.get("n") or [None])[0]
            try:
                n = None if n_raw is None else max(0, int(n_raw))
            except ValueError:
                n = None
            return 200, "application/x-ndjson", tracer.to_jsonl(n).encode()
        if route == "/tenants":
            # the chip-budget view: per-tenant spend (device-seconds by
            # tier, wire bytes, tokens/examples, HBM) + merged outcome
            # books + the conservation verdicts, plus which per-tenant
            # SLO rules fire right now (from this server's ledger)
            doc = _resourcemeter.snapshot()
            led = (self._owned_ledger or self._attached_ledger
                   or _runledger.current())
            if led is not None:
                try:
                    st = led.alert_status()
                    doc["slo_firing"] = [
                        r for r in st.get("firing", [])
                        if "tenant" in str(r)]
                except Exception:
                    pass
            return json_response(doc)
        return None

    @staticmethod
    def _parse_deadline(req: dict, headers: dict):
        """The ONE deadline contract for every POST route: the JSON
        field wins over the X-Deadline-Ms header (case-insensitive —
        HTTP/2 proxies lowercase it); both are a RELATIVE ms budget.
        Returns (deadline_ms or None, error_response or None)."""
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is None:
            deadline_ms = next(
                (v for k, v in headers.items()
                 if k.lower() == "x-deadline-ms"), None)
        if deadline_ms is None:
            return None, None
        try:
            deadline_ms = float(deadline_ms)
        except (TypeError, ValueError):
            return None, json_response(
                {"error": f"bad deadline_ms: {deadline_ms!r}"}, 400)
        if not math.isfinite(deadline_ms):
            # json.loads parses bare NaN/Infinity; a NaN budget makes
            # every deadline comparison False — admitted, then shed
            # with a misleading 429. Malformed input is a 400.
            return None, json_response(
                {"error": f"deadline_ms must be finite, "
                          f"got {deadline_ms!r}"}, 400)
        return deadline_ms, None

    @staticmethod
    def _extract_tenant(req: dict, headers: dict):
        """The ONE tenant contract, mirroring _parse_deadline: the JSON
        `tenant` field wins over the X-Tenant header (case-insensitive),
        falling back to the ambient tenant jsonhttp attached from the
        same header — so the result is always a concrete interned name
        (DEFAULT_TENANT when nobody said anything)."""
        tenant = req.get("tenant")
        if tenant is None:
            tenant = _tenancy.from_headers(headers)
        return _tenancy.intern(tenant)

    @staticmethod
    def _shed_response(e):
        """Shed, not failed: 429 + Retry-After (integer delta-seconds
        per RFC 9110; the body keeps ms precision)."""
        retry_after = max(0.05, getattr(e, "retry_after", 0.0) or 0.05)
        return json_response(
            {"error": str(e), "shed": True,
             "stage": getattr(e, "stage", "admission"),
             "retry_after_ms": round(retry_after * 1e3, 1)},
            429,
            headers={"Retry-After": str(max(1, math.ceil(retry_after)))})

    def _post(self, path, body, headers):
        if path == "/generate":
            return self._post_generate(body, headers)
        if path != "/predict":
            return None
        req = json.loads(body or b"{}")
        if "features" not in req:
            return json_response({"error": "missing 'features'"}, 400)
        try:
            feats = np.asarray(req["features"], np.float32)
        except (ValueError, TypeError) as e:  # ragged / non-numeric
            return json_response({"error": f"bad features: {e}"}, 400)
        if feats.ndim == 0 or feats.size == 0:
            return json_response(
                {"error": "features must be a non-empty example array"}, 400)
        single = feats.ndim == 1
        if single:
            feats = feats[None]
        deadline_ms, err = self._parse_deadline(req, headers)
        if err is not None:
            return err
        t0 = time.perf_counter()
        try:
            # the request's serving span: nests under jsonhttp's
            # http/server span (which joined the caller's traceparent,
            # or rooted a fresh trace) on this handler thread
            tenant = self._extract_tenant(req, headers)
            sp = _tracing.span("serve/predict",
                               examples=int(feats.shape[0]),
                               tenant=tenant)
            with sp:
                out = self.inference.output(feats, deadline_ms=deadline_ms,
                                            tenant=tenant)
        except RequestValidationError as e:  # the client's fault
            return json_response({"error": str(e)}, 400)
        except (RequestRejected, DeadlineExceeded) as e:
            # shed, not failed: 429 tells clients/load-balancers to back
            # off and retry later; 503 stays reserved for GET /health
            return self._shed_response(e)
        except Exception as e:
            # anything else (shutdown race, model/XLA failure — including
            # server-side ValueErrors) is a server fault: 500, so
            # clients/load-balancers retry or fail over (JsonHttpServer's
            # catch-all would mislabel it a 400)
            return json_response({"error": f"{type(e).__name__}: {e}"}, 500)
        dt = time.perf_counter() - t0
        self.latency.record(dt)
        # exemplar link: the histogram keeps (value, trace_id) on new
        # bucket maxima, so a p99 outlier in the scrape resolves via
        # `cli trace` to the exact trace that produced it. sp.context is
        # None when tracing is off (NULL_SPAN) — a plain observation.
        ctx = sp.context
        self._m_latency.observe(
            dt, trace_id=ctx.trace_id if ctx is not None else None)
        with _tracing.span("serve/respond"):
            if isinstance(out, list):  # multi-output graph: one entry
                # per head
                preds = [np.asarray(o)[0].tolist() if single
                         else np.asarray(o).tolist() for o in out]
            else:
                out = np.asarray(out)
                preds = (out[0] if single else out).tolist()
            return json_response({"predictions": preds})

    def _post_generate(self, body, headers):
        """POST /generate — the autoregressive decode route.

            {"prompt": [token ids...], "max_tokens": 32,
             "tenant": "...", "deadline_ms": 500, "stream": false}

        Non-streaming: one JSON body {"tokens": [...], "version": v}.
        `"stream": true`: a chunked application/x-ndjson response — one
        {"token": id} line per emitted token as it is produced, closed
        by a {"done": true, "tokens": [...]} line (or an {"error": ...}
        line if the request was shed mid-decode). Same deadline/429
        contract as /predict."""
        if self.decode is None:
            return json_response(
                {"error": "decode engine not enabled (start the server "
                          "with decode_slots > 0 / --decodeSlots)"}, 404)
        req = json.loads(body or b"{}")
        if "prompt" not in req:
            return json_response({"error": "missing 'prompt'"}, 400)
        deadline_ms, err = self._parse_deadline(req, headers)
        if err is not None:
            return err
        tenant = self._extract_tenant(req, headers)
        max_tokens = req.get("max_tokens")
        if max_tokens is not None:
            try:
                max_tokens = int(max_tokens)
            except (TypeError, ValueError):
                return json_response(
                    {"error": f"bad max_tokens: {max_tokens!r}"}, 400)
        kw = dict(max_new_tokens=max_tokens, tenant=tenant,
                  deadline_ms=deadline_ms)
        stream = bool(req.get("stream", False))
        t0 = time.perf_counter()
        try:
            with _tracing.span("serve/generate", tenant=tenant,
                               stream=stream):
                if not stream:
                    toks = self.decode.generate_sync(req["prompt"], **kw)
                    self.latency.record(time.perf_counter() - t0)
                    return json_response(
                        {"tokens": toks,
                         "version": self.decode.version})
                import queue as _queue

                emitted: "_queue.Queue" = _queue.Queue()
                fut = self.decode.generate(
                    req["prompt"], on_token=emitted.put_nowait, **kw)
        except RequestValidationError as e:
            return json_response({"error": str(e)}, 400)
        except (RequestRejected, DeadlineExceeded) as e:
            return self._shed_response(e)
        except Exception as e:
            return json_response({"error": f"{type(e).__name__}: {e}"},
                                 500)

        # the wedged-engine backstop the non-streaming route gets from
        # generate_sync: a deadline-carrying stream gives up (and sheds,
        # race-safely — the engine's own shed may win) a grace past its
        # deadline instead of pinning the handler thread forever
        from deeplearning4j_tpu.serving.decode import _WAIT_SHED_GRACE

        give_up = (None if deadline_ms is None
                   else t0 + float(deadline_ms) / 1e3 + _WAIT_SHED_GRACE)

        def lines():
            # drain tokens as the engine emits them; the final line
            # carries the whole-request verdict (mid-stream sheds can
            # no longer change the status code — it is on the wire)
            while True:
                try:
                    t = emitted.get(timeout=0.05)
                except _queue.Empty:
                    if fut.done() and emitted.empty():
                        break
                    if give_up is not None \
                            and time.perf_counter() >= give_up:
                        self.decode._fail(
                            fut,
                            DeadlineExceeded(
                                "deadline expired waiting on a stalled "
                                "decode engine", stage="wait"),
                            tenant, outcome="shed", stage="wait",
                            reason="expired")
                        break
                    continue
                yield (json.dumps({"token": int(t)}) + "\n").encode()
            try:
                toks = fut.result(timeout=0)
                yield (json.dumps(
                    {"done": True, "tokens": toks,
                     "version": self.decode.version}) + "\n").encode()
            except Exception as e:
                yield (json.dumps(
                    {"error": f"{type(e).__name__}: {e}",
                     "shed": isinstance(
                         e, (RequestRejected, DeadlineExceeded))})
                    + "\n").encode()

        return 200, "application/x-ndjson", lines()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> int:
        return self._server.start()

    def stop(self):
        self._server.stop()
        if self.decode is not None:
            self.decode.shutdown()
        self.inference.shutdown()
        if self._owned_ledger is not None:
            self._owned_ledger.close()
        elif self._attached_ledger is not None:
            _runledger.detach(self._attached_ledger)

    def join(self):
        self._server.join()


def main(argv=None):
    """CLI: serve a saved model zip / Keras h5 over REST.

        python -m deeplearning4j_tpu.serving.inference_server \
            --modelPath model.zip --port 9100 --maxBatchSize 64 \
            --batchTimeoutMs 2 --warmupShape 784
    """
    ap = argparse.ArgumentParser(description="model inference REST server")
    ap.add_argument("--modelPath", required=True)
    ap.add_argument("--port", type=int, default=9100)
    ap.add_argument("--maxBatchSize", type=int, default=64)
    ap.add_argument("--batchTimeoutMs", type=float, default=2.0)
    ap.add_argument("--buckets", default=None,
                    help="comma-separated bucket sizes (default: powers of "
                         "two up to maxBatchSize)")
    ap.add_argument("--warmupShape", default=None,
                    help="comma-separated feature shape to precompile all "
                         "buckets before the port opens, e.g. 784 or 28,28,1")
    ap.add_argument("--replicas", type=int, default=1,
                    help=">=2 serves through a self-healing ReplicaPool: "
                         "unhealthy replicas are evicted and respawned")
    ap.add_argument("--queueCapacity", type=int, default=1024,
                    help="bounded request queue: admission returns 429 "
                         "instead of queueing past this depth (0 = "
                         "unbounded)")
    ap.add_argument("--defaultDeadlineMs", type=float, default=None,
                    help="latency budget applied to requests that carry "
                         "no deadline_ms of their own")
    ap.add_argument("--requestTimeout", type=float, default=30.0,
                    help="per-connection socket read timeout (slowloris "
                         "protection); 0 disables")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="record a persistent run ledger (metrics "
                         "samples + SLO rule verdicts) to this path; "
                         "GET /alerts serves the live rule states")
    ap.add_argument("--decodeSlots", type=int, default=0,
                    help=">0 mounts the continuous-batching decode "
                         "engine (POST /generate) with this many slots "
                         "(recurrent models only)")
    ap.add_argument("--decodeEos", type=int, default=None,
                    help="EOS token id ending a generated sequence early")
    ap.add_argument("--decodeMaxTokens", type=int, default=64,
                    help="default max_tokens for /generate requests")
    ap.add_argument("--meter", action="store_true",
                    help="arm per-tenant resource metering "
                         "(utils/resourcemeter): GET /tenants then "
                         "reports device-seconds/wire/HBM spend, not "
                         "just admission books")
    args = ap.parse_args(argv)
    if args.meter:
        _resourcemeter.enable()
    from deeplearning4j_tpu.cli import guess_and_load_model

    model = guess_and_load_model(args.modelPath)
    buckets = (None if args.buckets is None
               else [int(b) for b in args.buckets.split(",")])
    warmup = (None if args.warmupShape is None
              else tuple(int(d) for d in args.warmupShape.split(",")))
    server = InferenceServer(
        model, port=args.port, max_batch_size=args.maxBatchSize,
        batch_timeout_ms=args.batchTimeoutMs, buckets=buckets,
        warmup_shape=warmup, n_replicas=args.replicas,
        queue_capacity=args.queueCapacity,
        default_deadline_ms=args.defaultDeadlineMs,
        request_timeout=args.requestTimeout,
        run_ledger=args.ledger,
        decode_slots=args.decodeSlots,
        decode_eos_token=args.decodeEos,
        decode_max_tokens=args.decodeMaxTokens,
    )
    # operator surface: opt in to real log output, then announce through
    # the package logger (library code never prints — lint CC006)
    from deeplearning4j_tpu import configure_logging

    if all(isinstance(h, logging.NullHandler) for h in logger.handlers):
        configure_logging()
    port = server.start()
    logger.info("inference server listening on :%d (buckets %s)",
                port, server.inference.buckets)
    try:
        server.join()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
