"""Training listeners.

Analog of the reference's IterationListener/TrainingListener SPI
(optimize/api/, optimize/listeners/): ScoreIterationListener,
PerformanceListener (samples/sec + ETL time), CollectScoresIterationListener,
EvaluativeListener. The listener callback receives a small info dict; score
is fetched as a host scalar only when a listener actually wants it, so
listeners do not force device syncs on every step.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class IterationListener:
    """SPI (reference: optimize/api/IterationListener.java)."""

    def iteration_done(self, model, iteration: int, info: dict) -> None:
        raise NotImplementedError

    def on_epoch_start(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass


class ScoreIterationListener(IterationListener):
    """Log the score every `frequency` iterations (reference:
    optimize/listeners/ScoreIterationListener.java)."""

    def __init__(self, frequency: int = 10, print_fn: Optional[Callable] = None):
        self.frequency = max(1, frequency)
        self.print_fn = print_fn or (lambda s: logger.info(s))

    def iteration_done(self, model, iteration, info):
        if iteration % self.frequency == 0:
            score = float(info["score"]())
            self.print_fn(f"Score at iteration {iteration} is {score}")


class PerformanceListener(IterationListener):
    """Throughput listener (reference: PerformanceListener.java — iterations
    /sec, samples/sec, ETL time)."""

    def __init__(self, frequency: int = 10, print_fn: Optional[Callable] = None):
        self.frequency = max(1, frequency)
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self._last_time = None
        self._samples = 0
        self._iters = 0

    def iteration_done(self, model, iteration, info):
        now = time.perf_counter()
        self._samples += info.get("batch_size", 0)
        self._iters += 1
        if self._last_time is None:
            self._last_time = now
            return
        if self._iters % self.frequency == 0:
            dt = now - self._last_time
            if dt > 0:
                self.print_fn(
                    f"iter {iteration}: {self._iters / dt:.1f} it/s, "
                    f"{self._samples / dt:.1f} samples/s, "
                    f"etl {info.get('etl_ms', 0.0):.1f} ms"
                )
            self._last_time = now
            self._samples = 0
            self._iters = 0


class CollectScoresIterationListener(IterationListener):
    """Accumulate (iteration, score) pairs (reference:
    CollectScoresIterationListener.java)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, info):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(info["score"]())))


class EvaluativeListener(IterationListener):
    """Periodically evaluate on a held-out set (reference:
    EvaluativeListener.java)."""

    def __init__(self, data_iterator, frequency: int = 100, print_fn=None):
        self.iterator = data_iterator
        self.frequency = max(1, frequency)
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self.last_evaluation = None

    def iteration_done(self, model, iteration, info):
        if iteration > 0 and iteration % self.frequency == 0:
            ev = model.evaluate(self.iterator)
            self.last_evaluation = ev
            self.print_fn(f"iter {iteration}: accuracy={ev.accuracy():.4f}")


class ComposableIterationListener(IterationListener):
    def __init__(self, *listeners):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration, info):
        for listener in self.listeners:
            listener.iteration_done(model, iteration, info)
