"""Activation functions.

Covers the full activation enum the reference's config DSL accepts
(org.nd4j.linalg.activations.Activation, accepted by
NeuralNetConfiguration.Builder.activation(...) — see
deeplearning4j-nn/.../nn/conf/NeuralNetConfiguration.java and the
gradient-check whitelist at gradientcheck/GradientCheckUtil.java:48-59),
plus an SPI for custom activations (the reference's IActivation).

All functions are pure jnp element-wise maps; XLA fuses them into the
surrounding matmul/conv so there is no per-op dispatch cost. RReLU's random
alpha at train time needs an rng key, so activation_fn takes an optional key.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

# name -> fn(x, key=None, training=False) -> jnp.ndarray
_REGISTRY: Dict[str, Callable] = {}


def register_activation(name: str, fn: Callable) -> None:
    """Custom-activation SPI (reference: IActivation implementations)."""
    _REGISTRY[name.lower()] = fn


def _simple(name):
    def deco(fn):
        register_activation(name, lambda x, key=None, training=False: fn(x))
        return fn

    return deco


@_simple("identity")
def identity(x):
    return x


@_simple("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@_simple("tanh")
def tanh(x):
    return jnp.tanh(x)


@_simple("relu")
def relu(x):
    return jax.nn.relu(x)


@_simple("relu6")
def relu6(x):
    return jax.nn.relu6(x)


@_simple("leakyrelu")
def leakyrelu(x):
    # Reference default alpha 0.01 (ActivationLReLU.DEFAULT_ALPHA)
    return jax.nn.leaky_relu(x, negative_slope=0.01)


@_simple("elu")
def elu(x):
    return jax.nn.elu(x)


@_simple("selu")
def selu(x):
    return jax.nn.selu(x)


@_simple("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@_simple("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@_simple("hardtanh")
def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


@_simple("hardsigmoid")
def hardsigmoid(x):
    # Reference ActivationHardSigmoid: clip(0.2*x + 0.5, 0, 1)
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@_simple("cube")
def cube(x):
    return x * x * x


@_simple("rationaltanh")
def rationaltanh(x):
    # Reference ActivationRationalTanh: 1.7159 * tanh_approx(2x/3) where
    # tanh_approx(y) = sign(y) * (1 - 1/(1+|y|+y^2+1.41645*y^4))
    y = 2.0 * x / 3.0
    a = jnp.abs(y)
    approx = jnp.sign(y) * (1.0 - 1.0 / (1.0 + a + y * y + 1.41645 * (y**4)))
    return 1.7159 * approx


@_simple("rectifiedtanh")
def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


@_simple("swish")
def swish(x):
    return jax.nn.silu(x)


@_simple("gelu")
def gelu(x):
    return jax.nn.gelu(x)


@_simple("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@_simple("thresholdedrelu")
def thresholdedrelu(x):
    return jnp.where(x > 1.0, x, 0.0)


@_simple("softmax")
def softmax(x):
    # Row softmax over the feature axis (last axis), matching the reference's
    # 2d [batch, nOut] / time-distributed conventions.
    return jax.nn.softmax(x, axis=-1)


@_simple("logsoftmax")
def logsoftmax(x):
    return jax.nn.log_softmax(x, axis=-1)


def _rrelu(x, key=None, training=False, lower=1.0 / 8.0, upper=1.0 / 3.0):
    """Randomized leaky ReLU (reference ActivationRReLU: U[l,u] alpha when
    training, (l+u)/2 at inference)."""
    if training and key is not None:
        alpha = jax.random.uniform(key, x.shape, minval=lower, maxval=upper, dtype=x.dtype)
    else:
        alpha = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, alpha * x)


register_activation("rrelu", _rrelu)


class Activation:
    """Enum-style names (string constants) mirroring the reference enum."""

    CUBE = "cube"
    ELU = "elu"
    HARDSIGMOID = "hardsigmoid"
    HARDTANH = "hardtanh"
    IDENTITY = "identity"
    LEAKYRELU = "leakyrelu"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    RELU = "relu"
    RRELU = "rrelu"
    SELU = "selu"
    SIGMOID = "sigmoid"
    SOFTMAX = "softmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    SWISH = "swish"
    GELU = "gelu"
    TANH = "tanh"


def activation_fn(name: str) -> Callable:
    """Look up an activation by name. Returned callable has signature
    fn(x, key=None, training=False)."""
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown activation {name!r}; known: {sorted(_REGISTRY)}"
        ) from None


def apply_activation(name: str, x, key: Optional[jax.Array] = None, training: bool = False):
    return activation_fn(name)(x, key=key, training=training)
