"""Tenant-scoped resource metering — the cross-tier chip-budget ledger.

utils/tenancy.py answers "who is this request"; this module answers
"what did each tenant spend", with one vocabulary across every tier:

* **device-seconds** — training windows ride devprof's sampled
  `block_until_ready` cadence (the dt between samples, divided over the
  steps it covers — no new sync points); decode steps and serving
  forwards are already wall-timed on their own threads, and each
  step/forward's duration is split across the tenants it served
  (slots / rows), so weighted-fair scheduling becomes auditable SPEND,
  not just slot order.
* **HBM-resident bytes** — per-net params+updater (the devprof/PR 9
  accounting) and per-version decode weights, keyed by source so a
  dropped weight version releases its bytes.
* **wire bytes** — gradient all-reduce payload (training), paramserver
  push/pull bodies (both sides of the boundary).
* **tokens / examples** and the **outcome books** (AdmissionBooks, the
  conservation law's home — moved here from parallel/inference so the
  serving, decode, and REST tiers share one implementation).

Everything lands in the process metrics registry as
`tenant_device_seconds_total{tenant,tier}` /
`tenant_hbm_bytes{tenant}` / `tenant_wire_bytes_total{tenant,tier}` /
`tenant_tokens_total{tenant}` / `tenant_examples_total{tenant,tier}`,
next to `process_device_seconds_total{tier}` — the right-hand side of
the spend conservation invariant (per-tenant device-seconds sum to the
process total per tier, because both are incremented in the same hook).
The run ledger's default sampler records these series like any other,
so `cli tenants --ledger <run>` rebuilds the live `/tenants` spend
table from the artifact alone: both views parse the SAME flat
scalar-values vocabulary through `spend_table()`.

Off-path contract (the house bar, same as runledger.note_fit_step):
every `note_*` hook begins with one module-global read — an unmetered
process pays a None check per call, pinned <10µs by test. Metering is
armed process-wide with `enable()` (cli/bench/server flags do this) and
books registration is always-on but init-time-only, so engines never
branch on the meter in their hot loops beyond that one read.
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
import threading
import weakref
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import tenancy as _tenancy

TIER_TRAINING = "training"
TIER_SERVING = "serving"
TIER_DECODE = "decode"
TIER_PARAMSERVER = "paramserver"

TIERS = (TIER_TRAINING, TIER_SERVING, TIER_DECODE, TIER_PARAMSERVER)


class AdmissionBooks:
    """Exact request accounting under the conservation law

        admitted == completed + shed + failed

    with per-"stage/reason" shed breakdowns. Admission REFUSALS land in
    `rejected`, outside the law — the request never entered the system.
    Keyed by tenant (None books under the default tenant), so
    multi-tenant hosting's books stay exact per customer. The shared
    implementation every tier uses: ParallelInference, the decode
    engine, and the REST layer all book through this class. NOT
    internally locked — callers mutate under their own admission lock,
    exactly as the inline counters this class replaced were."""

    _KEYS = ("admitted", "completed", "shed", "failed", "rejected")

    def __init__(self):
        self._tenants: dict = {}

    def _t(self, tenant):
        t = self._tenants.get(tenant)
        if t is None:
            t = self._tenants[tenant] = {
                "admitted": 0, "completed": 0, "shed": 0, "failed": 0,
                "rejected": 0, "shed_by": {}}
        return t

    def admit(self, tenant=None):
        self._t(tenant)["admitted"] += 1

    def complete(self, tenant=None):
        self._t(tenant)["completed"] += 1

    def fail(self, tenant=None):
        self._t(tenant)["failed"] += 1

    def shed(self, stage: str, reason: str, tenant=None,
             admitted: bool = True):
        t = self._t(tenant)
        key = f"{stage}/{reason}"
        t["shed_by"][key] = t["shed_by"].get(key, 0) + 1
        t["shed" if admitted else "rejected"] += 1

    def totals(self) -> dict:
        agg = {k: 0 for k in self._KEYS}
        agg["shed_by"] = {}
        for t in self._tenants.values():
            for k in self._KEYS:
                agg[k] += t[k]
            for sb, v in t["shed_by"].items():
                agg["shed_by"][sb] = agg["shed_by"].get(sb, 0) + v
        return agg

    def per_tenant(self) -> dict:
        return {
            (_tenancy.DEFAULT_TENANT if t is None else t): {
                **{k: b[k] for k in self._KEYS},
                "shed_by": dict(b["shed_by"]),
                "conservation_ok":
                    b["admitted"] == b["completed"] + b["shed"] + b["failed"],
            }
            for t, b in self._tenants.items()
        }

    def conservation_ok(self) -> bool:
        """The law, per tenant AND therefore in aggregate."""
        return all(
            t["admitted"] == t["completed"] + t["shed"] + t["failed"]
            for t in self._tenants.values())


# -- always-on books registry -------------------------------------------------
#
# Engines register their AdmissionBooks at construction (init-time, not
# hot-path) so GET /tenants and `cli tenants` can aggregate outcome
# books across tiers even when spend metering was never enabled.
# Weak-valued: a shut-down engine's books disappear with it.

_books_lock = threading.Lock()
_BOOKS: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()
_books_seq = 0


def register_books(tier: str, books: AdmissionBooks) -> None:
    global _books_seq
    with _books_lock:
        _books_seq += 1
        _BOOKS[(tier, _books_seq)] = books


def books_by_tier() -> Dict[str, List[AdmissionBooks]]:
    out: Dict[str, List[AdmissionBooks]] = {}
    with _books_lock:
        items = list(_BOOKS.items())
    for (tier, _), b in items:
        out.setdefault(tier, []).append(b)
    return out


def merged_books(tier: Optional[str] = None) -> dict:
    """Per-tenant outcome books merged across every live book-keeper
    (optionally one tier): the cross-tier conservation view."""
    merged: Dict[str, dict] = {}
    for t, books in books_by_tier().items():
        if tier is not None and t != tier:
            continue
        for b in books:
            for tenant, rec in b.per_tenant().items():
                agg = merged.setdefault(tenant, {
                    "admitted": 0, "completed": 0, "shed": 0,
                    "failed": 0, "rejected": 0})
                for k in agg:
                    agg[k] += rec[k]
    for rec in merged.values():
        rec["conservation_ok"] = (
            rec["admitted"]
            == rec["completed"] + rec["shed"] + rec["failed"])
    return merged


# -- the meter ----------------------------------------------------------------

class ResourceMeter:
    """Per-tenant per-tier spend accounting on the shared metrics
    registry. One instance per process (module global, `enable()`);
    internally locked — hooks are called from fit threads, the decode
    loop, serving dispatchers, and HTTP handlers concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        reg = _metrics.get_registry()
        self._c_device = reg.counter(
            "tenant_device_seconds_total",
            "device time attributed to a tenant, by tier (training: "
            "devprof sampled windows; decode/serving: step/forward "
            "wall time split over the tenants served)",
            ("tenant", "tier"))
        self._c_process = reg.counter(
            "process_device_seconds_total",
            "device time metered for the whole process, by tier — the "
            "right-hand side of the per-tenant spend conservation "
            "invariant", ("tier",))
        self._c_wire = reg.counter(
            "tenant_wire_bytes_total",
            "interconnect/network payload bytes attributed to a tenant, "
            "by tier (gradient all-reduce, paramserver push/pull)",
            ("tenant", "tier"))
        self._c_tokens = reg.counter(
            "tenant_tokens_total",
            "decode tokens emitted for a tenant", ("tenant",))
        self._c_examples = reg.counter(
            "tenant_examples_total",
            "examples processed for a tenant, by tier", ("tenant",))
        self._g_hbm = reg.gauge(
            "tenant_hbm_bytes",
            "HBM-resident bytes attributed to a tenant (net params + "
            "updater state, decode weight versions), summed over "
            "sources", ("tenant",))
        # source -> (tenant, bytes): a dropped source (old decode weight
        # version, a net going away) releases its bytes from the gauge
        self._hbm: Dict[str, Tuple[str, float]] = {}

    # -- charging (all tenant args are raw; interning happens here) ----------

    def charge_device_seconds(self, tenant, tier: str, seconds: float,
                              examples: int = 0) -> None:
        if seconds <= 0:
            return
        t = _tenancy.intern(tenant)
        self._c_device.labels(t, tier).inc(seconds)
        self._c_process.labels(tier).inc(seconds)
        if examples:
            self._c_examples.labels(t).inc(examples)

    def charge_device_split(self, shares: Dict[str, float], tier: str,
                            seconds: float) -> None:
        """Split one measured window across tenants proportional to
        `shares` (slots or rows served). The process total gets the
        whole window ONCE — per-tenant spend sums to it exactly."""
        if seconds <= 0 or not shares:
            return
        total = float(sum(shares.values()))
        if total <= 0:
            return
        for tenant, w in shares.items():
            self._c_device.labels(_tenancy.intern(tenant), tier).inc(
                seconds * float(w) / total)
        self._c_process.labels(tier).inc(seconds)

    def charge_wire(self, tenant, tier: str, nbytes: int) -> None:
        if nbytes > 0:
            self._c_wire.labels(_tenancy.intern(tenant), tier).inc(nbytes)

    def charge_tokens(self, tenant, n: int) -> None:
        if n > 0:
            self._c_tokens.labels(_tenancy.intern(tenant)).inc(n)

    def set_hbm(self, tenant, source: str, nbytes: float) -> None:
        """Point-in-time HBM attribution for one `source` (a net's
        params, one decode weight version). 0 releases the source."""
        t = _tenancy.intern(tenant)
        with self._lock:
            if nbytes <= 0:
                self._hbm.pop(source, None)
            else:
                self._hbm[source] = (t, float(nbytes))
            sums: Dict[str, float] = {}
            for ten, b in self._hbm.values():
                sums[ten] = sums.get(ten, 0.0) + b
            for ten in {t, *sums}:
                self._g_hbm.labels(ten).set(sums.get(ten, 0.0))

    # -- readout --------------------------------------------------------------

    def snapshot(self) -> dict:
        """The /tenants document: per-tenant spend (from the registry's
        flat scalar view — the SAME parse the ledger replay uses), the
        merged outcome books, and the conservation verdicts."""
        values = _metrics.get_registry().scalar_values()
        table = spend_table(values)
        books = merged_books()
        tenants = sorted({*table, *books})
        return {
            "tenants": {
                t: {**table.get(t, _empty_spend()),
                    "books": books.get(t)}
                for t in tenants
            },
            "books_by_tier": {
                tier: merged_books(tier) for tier in books_by_tier()
            },
            "conservation": conservation(values, books),
            "registry_tenants": _tenancy.get_tenant_registry().tenants(),
        }


_METER: Optional[ResourceMeter] = None


def enable() -> ResourceMeter:
    """Arm process-wide metering (idempotent). Until this runs, every
    note_* hook is one module-global read returning immediately."""
    global _METER
    if _METER is None:
        _METER = ResourceMeter()
    return _METER


def disable() -> None:
    """Tests only: restore the unmetered off-path."""
    global _METER
    _METER = None


def get_meter() -> Optional[ResourceMeter]:
    return _METER


def is_enabled() -> bool:
    return _METER is not None


def snapshot() -> dict:
    """The /tenants document whether or not spend metering is armed:
    metered processes get the full spend+books view; unmetered ones
    still get the always-on outcome books and the conservation verdict
    (vacuously spend-ok), plus a note saying why spend is empty."""
    m = _METER
    if m is not None:
        return m.snapshot()
    values = _metrics.get_registry().scalar_values()
    books = merged_books()
    return {
        "tenants": {t: {**_empty_spend(), "books": b}
                    for t, b in books.items()},
        "books_by_tier": {tier: merged_books(tier)
                          for tier in books_by_tier()},
        "conservation": conservation(values, books),
        "registry_tenants": _tenancy.get_tenant_registry().tenants(),
        "note": "spend metering disabled (resourcemeter.enable()): "
                "outcome books only",
    }


# -- hot-path hooks (one module-global read when unmetered) -------------------

def note_device_window(net, dt: float, examples: int = 0) -> None:
    """devprof's sampled window: `dt` seconds of device time for `net`
    since the last sample, charged to the net's registered tenant
    (set_tenant / register_net) in the training tier. Also refreshes
    the net's HBM attribution from the devprof byte cache — no new
    device work, those sums are already cached per net."""
    m = _METER
    if m is None:
        return
    tenant = getattr(net, "_tenant", None)
    m.charge_device_seconds(tenant, TIER_TRAINING, dt, examples=examples)
    st = getattr(net, "_devprof_state", None)
    if st and st.get("params_bytes"):
        m.set_hbm(tenant, f"net_params_{id(net)}",
                  st["params_bytes"] + (st.get("updater_bytes") or 0))


def note_decode_step(dt: float, slots_by_tenant: Dict[str, int]) -> None:
    m = _METER
    if m is None:
        return
    m.charge_device_split(slots_by_tenant, TIER_DECODE, dt)


def note_serving_forward(dt: float, rows_by_tenant: Dict[str, int]) -> None:
    m = _METER
    if m is None:
        return
    m.charge_device_split(rows_by_tenant, TIER_SERVING, dt)


def note_tokens(tenant, n: int) -> None:
    m = _METER
    if m is None:
        return
    m.charge_tokens(tenant, n)


def note_wire(tenant, tier: str, nbytes: int) -> None:
    m = _METER
    if m is None:
        return
    m.charge_wire(tenant, tier, nbytes)


def note_ps_pull(tenant, seconds: float) -> None:
    """Book sparse-embedding pull wall time (the wire wait the pipeline
    could not hide) against `tenant` under the paramserver tier — the
    time axis next to the wire bytes `note_wire` already books server-
    side. No-op until a meter is enabled."""
    m = _METER
    if m is None:
        return
    m.charge_device_seconds(tenant, TIER_PARAMSERVER, seconds)


def note_hbm(tenant, source: str, nbytes: float) -> None:
    m = _METER
    if m is None:
        return
    m.set_hbm(tenant, source, nbytes)


def register_net(net, tenant) -> None:
    """Give a training net the same identity serving uses: its devprof
    windows, all-reduce wire bytes, and paramserver RPCs are booked
    under `tenant` from here on."""
    net._tenant = _tenancy.intern(tenant)


# -- the shared spend-table parse (live /tenants AND ledger replay) -----------

_SERIES_RE = re.compile(r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
                        r"(?:\{(?P<labels>.*)\})?$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')

_SPEND_SERIES = ("tenant_device_seconds_total", "tenant_wire_bytes_total",
                 "tenant_tokens_total", "tenant_examples_total",
                 "tenant_hbm_bytes", "process_device_seconds_total")


def _empty_spend() -> dict:
    return {"device_seconds": {}, "wire_bytes": {}, "tokens": 0.0,
            "examples": 0.0, "hbm_bytes": 0.0}


def spend_table(values: Dict[str, float]) -> Dict[str, dict]:
    """Per-tenant spend from a flat `scalar_values()`-vocabulary dict —
    live registry and recorded run-ledger samples parse identically, so
    `cli tenants --ledger` reproduces `/tenants` by construction."""
    out: Dict[str, dict] = {}
    for key, v in values.items():
        mt = _SERIES_RE.match(key)
        if mt is None or mt.group("name") not in _SPEND_SERIES:
            continue
        name = mt.group("name")
        labels = dict(_LABEL_RE.findall(mt.group("labels") or ""))
        tenant = labels.get("tenant")
        if tenant is None:
            continue
        rec = out.setdefault(tenant, _empty_spend())
        tier = labels.get("tier", "")
        if name == "tenant_device_seconds_total":
            rec["device_seconds"][tier] = \
                rec["device_seconds"].get(tier, 0.0) + v
        elif name == "tenant_wire_bytes_total":
            rec["wire_bytes"][tier] = rec["wire_bytes"].get(tier, 0.0) + v
        elif name == "tenant_tokens_total":
            rec["tokens"] += v
        elif name == "tenant_examples_total":
            rec["examples"] += v
        elif name == "tenant_hbm_bytes":
            rec["hbm_bytes"] += v
    return out


def process_device_seconds(values: Dict[str, float]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for key, v in values.items():
        mt = _SERIES_RE.match(key)
        if mt is None or mt.group("name") != "process_device_seconds_total":
            continue
        labels = dict(_LABEL_RE.findall(mt.group("labels") or ""))
        out[labels.get("tier", "")] = v
    return out


def conservation(values: Dict[str, float],
                 books: Optional[dict] = None,
                 rel_tol: float = 1e-6) -> dict:
    """The invariant the chaos presets and the overload bench gate on:
    per-tenant outcome books obey the conservation law, and per-tier
    tenant device-seconds sum (within float tolerance) to the process
    total metered for that tier."""
    if books is None:
        books = merged_books()
    table = spend_table(values)
    proc = process_device_seconds(values)
    per_tier_sum: Dict[str, float] = {}
    for rec in table.values():
        for tier, s in rec["device_seconds"].items():
            per_tier_sum[tier] = per_tier_sum.get(tier, 0.0) + s
    spend_ok = all(
        math.isclose(per_tier_sum.get(tier, 0.0), total,
                     rel_tol=rel_tol, abs_tol=1e-9)
        for tier, total in proc.items())
    books_ok = all(rec["conservation_ok"] for rec in books.values())
    return {
        "books_ok": books_ok,
        "spend_ok": spend_ok,
        "ok": books_ok and spend_ok,
        "device_seconds_by_tier": proc,
        "tenant_device_seconds_by_tier": per_tier_sum,
    }


# -- t1 smoke -----------------------------------------------------------------

def smoke() -> dict:
    """Own-interpreter tier-1 gate (`T1 TENANT BOOKS:` in scripts/t1.sh):
    two tenants through the decode smoke plus one metered fit, then
    asserts cross-tier conservation holds non-vacuously and that
    `cli tenants` renders the in-process view with exit 0."""
    import numpy as np

    enable()
    from deeplearning4j_tpu.serving import decode as _decode

    dec = _decode.smoke()
    # one metered fit under a named tenant: training spend lands next to
    # the decode tenants' in the same vocabulary
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.utils import devprof as _devprof

    conf = (NeuralNetConfiguration.builder().seed(7)
            .learning_rate(0.05).weight_init("xavier").list()
            .layer(DenseLayer(n_in=8, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=4, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init().set_tenant("trainer")
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
    prof = _devprof.get_profiler()
    prev_sample_every = prof.sample_every
    prof.sample_every = 1  # every step measures a device window
    try:
        with _tenancy.tenant_scope("trainer"):
            net.fit(x, y, batch_size=8, epochs=3, async_prefetch=False)
            prof.sample_now(net)
    finally:
        prof.sample_every = prev_sample_every

    values = _metrics.get_registry().scalar_values()
    cons = conservation(values)
    table = spend_table(values)
    trainer_sec = table.get("trainer", _empty_spend())[
        "device_seconds"].get(TIER_TRAINING, 0.0)
    decode_tenants = {t for t, rec in table.items()
                      if rec["device_seconds"].get(TIER_DECODE, 0.0) > 0}
    # non-vacuous: both decode tenants AND the metered fit actually spent
    moved = trainer_sec > 0 and {"a", "b"} <= decode_tenants
    from deeplearning4j_tpu.cli import main as cli_main

    cli_rc = cli_main(["tenants"])
    return {
        "decode_ok": bool(dec.get("ok")),
        "conservation": cons,
        "trainer_device_seconds": trainer_sec,
        "decode_tenants": sorted(decode_tenants),
        "moved": moved,
        "cli_tenants_rc": cli_rc,
        "ok": bool(dec.get("ok") and cons["ok"] and moved
                   and cli_rc == 0),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="tenant resource-meter smoke (tier-1 gate)")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args(argv)
    if not args.smoke:
        ap.error("nothing to do (pass --smoke)")
    report = smoke()
    sys.stdout.write(json.dumps(report, indent=1, default=str) + "\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    # `python -m` would otherwise run a SECOND copy of this module (as
    # __main__) whose _METER/_BOOKS globals are disjoint from the ones
    # decode/cli import — the smoke must arm the canonical instance
    from deeplearning4j_tpu.utils import resourcemeter as _canonical

    sys.exit(_canonical.main())
