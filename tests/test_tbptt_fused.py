"""Fused-TBPTT equivalence: the single-dispatch fused step
(`MultiLayerNetwork._build_tbptt_fused_step`) must produce the SAME
trajectory — params, updater state, scores, iteration count — as the
per-segment host loop it replaces (`_fit_tbptt`'s loop path).

The loop path is forced by attaching a listener (listeners pin the loop so
per-iteration callbacks see their iteration's params); the fused path is
the default for listener-free fits with no ragged tail. Reference
behavior being preserved: MultiLayerNetwork.doTruncatedBPTT
(nn/multilayer/MultiLayerNetwork.java:1333)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    GravesLSTM,
    InputType,
    LSTM,
    NeuralNetConfiguration,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.network import BackpropType
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.listeners import IterationListener


class _NoOpListener(IterationListener):
    """Forces `_fit_tbptt` onto the per-segment loop path."""

    def iteration_done(self, model, iteration, info):
        pass


def _seq_data(n=16, t=12, nin=3, nout=2, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, nin)).astype(np.float32)
    cs = np.cumsum(x[..., 0], axis=1)
    y = np.zeros((n, t, nout), np.float32)
    y[..., 0] = (cs <= 0).astype(np.float32)
    y[..., 1] = (cs > 0).astype(np.float32)
    return x, y


def _conf(fwd=4, bwd=4, *, cell=LSTM, dropout=0.0, updater="adam"):
    return (
        NeuralNetConfiguration.builder()
        .seed(7)
        .updater(updater)
        .learning_rate(0.02)
        .list()
        .layer(cell(n_out=8, activation="tanh", dropout=dropout))
        .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(3))
        .backprop_type(BackpropType.TRUNCATED_BPTT)
        .t_bptt_lengths(fwd, bwd)
        .build()
    )


def _max_tree_diff(a, b):
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return max(
        float(jnp.max(jnp.abs(jnp.asarray(x, jnp.float32)
                              - jnp.asarray(y, jnp.float32))))
        for x, y in zip(leaves_a, leaves_b)
    ) if leaves_a else 0.0


def _run_pair(conf_kwargs, data_kwargs=None, epochs=2, mask=False):
    """Train one net on the loop path, one on the fused path; return both."""
    x, y = _seq_data(**(data_kwargs or {}))
    fm = lm = None
    if mask:
        t = x.shape[1]
        lengths = np.random.default_rng(3).integers(t // 2, t + 1, x.shape[0])
        fm = (np.arange(t)[None, :] < lengths[:, None]).astype(np.float32)
        lm = fm.copy()
    ds = DataSet(x, y, features_mask=fm, labels_mask=lm)

    loop_net = MultiLayerNetwork(_conf(**conf_kwargs)).init()
    loop_net.add_listener(_NoOpListener())
    fused_net = MultiLayerNetwork(_conf(**conf_kwargs)).init()

    loop_net.fit(ds, epochs=epochs, async_prefetch=False)
    fused_net.fit(ds, epochs=epochs, async_prefetch=False)
    return loop_net, fused_net


@pytest.mark.parametrize("updater", ["adam", "nesterovs"])
def test_fused_matches_loop_params_and_updater(updater):
    loop_net, fused_net = _run_pair({"fwd": 4, "bwd": 4, "updater": updater})
    assert fused_net.iteration == loop_net.iteration == 2 * 3  # 12/4 seg
    assert _max_tree_diff(loop_net.params_list, fused_net.params_list) < 1e-6
    assert _max_tree_diff(loop_net.upd_state, fused_net.upd_state) < 1e-6
    assert abs(float(loop_net._score) - float(fused_net._score)) < 1e-6


def test_fused_matches_loop_with_backward_truncation():
    # bwd < fwd exercises the truncated loss builder inside the fused scan
    loop_net, fused_net = _run_pair({"fwd": 6, "bwd": 3})
    assert _max_tree_diff(loop_net.params_list, fused_net.params_list) < 1e-6
    assert _max_tree_diff(loop_net.upd_state, fused_net.upd_state) < 1e-6


def test_fused_matches_loop_with_dropout_rng():
    # dropout consumes the per-iteration rng — pins the fused path's
    # fold_in(key, t) derivation to the loop path's fold_in(key, iteration)
    loop_net, fused_net = _run_pair({"fwd": 4, "bwd": 4, "dropout": 0.5})
    assert _max_tree_diff(loop_net.params_list, fused_net.params_list) < 1e-6


def test_fused_matches_loop_with_masks():
    loop_net, fused_net = _run_pair({"fwd": 4, "bwd": 4}, mask=True)
    assert _max_tree_diff(loop_net.params_list, fused_net.params_list) < 1e-6


def test_fused_single_segment():
    # n_seg == 1: the fused step must skip the (empty) scan
    loop_net, fused_net = _run_pair({"fwd": 12, "bwd": 12})
    assert fused_net.iteration == loop_net.iteration == 2
    assert _max_tree_diff(loop_net.params_list, fused_net.params_list) < 1e-6


def test_ragged_tail_falls_back_to_loop():
    # T=10, seg=4 -> segments 4/4/2: fused path must decline; training
    # still runs and matches the loop exactly (both are the loop)
    loop_net, fused_net = _run_pair(
        {"fwd": 4, "bwd": 4}, data_kwargs={"t": 10})
    assert fused_net.iteration == loop_net.iteration == 2 * 3
    assert _max_tree_diff(loop_net.params_list, fused_net.params_list) < 1e-6


def test_graves_peepholes_fused():
    loop_net, fused_net = _run_pair({"fwd": 4, "bwd": 4, "cell": GravesLSTM})
    assert _max_tree_diff(loop_net.params_list, fused_net.params_list) < 1e-6
