"""Core NN engine: configs, layers, networks.

Analog of the reference's deeplearning4j-nn module (~64k LoC Java), rebuilt
as: declarative config dataclasses -> pure functional layer forwards ->
XLA-compiled networks. See SURVEY.md §2.1.
"""
