"""Process-global tenant identity — who is spending the chips.

Every tier of the stack already keeps *some* per-customer accounting
(the decode engine's weighted-fair books, ParallelInference's admission
books, the paramserver's RPC counters), but each grew its own notion of
"tenant": serving had real names, training had none, and nothing
crossed a process boundary. This module is the one shared identity
layer the resource meter (utils/resourcemeter) and every book-keeper
sit on:

* **Bounded interning** — `intern(name)` canonicalizes a raw tenant
  string (strip, length-cap, label-safe charset) and registers it in a
  process-global registry bounded at `max_tenants` (default 64, env
  `DL4J_MAX_TENANTS`). Past the cap, *new* names collapse into the
  `__other__` tenant: tenant names come from request headers, so an
  unbounded mapping would let any client explode the metrics registry
  and the run ledger one curl at a time (label-cardinality DoS — the
  same bound the kernel-family helper labels enforce).

* **Thread-local propagation** — `attach()`/`detach()`/`tenant_scope()`
  carry the active tenant across queue hops and worker threads exactly
  like utils/tracing carries the span context; `current_tenant()` is
  one thread-local read. The tenant rides NEXT TO the W3C traceparent:
  utils/jsonhttp attaches it server-side from the `X-Tenant` header and
  `tenant_headers()` injects it client-side, so a paramserver pull made
  from a metered training step carries the same identity the serving
  tier books under.

* **Header contract** — `X-Tenant` (case-insensitive, like
  `X-Deadline-Ms`); REST routes let an explicit JSON `tenant` field win
  over the header, both funnel through `intern()`.

Off-path cost: a process that never names a tenant pays one
thread-local read per hook (`current_tenant()` returns None), and the
registry holds only the default tenant. No repo imports here — the
metrics registry imports THIS module for exemplar tagging, so tenancy
stays at the bottom of the dependency stack.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

DEFAULT_TENANT = "default"

# the collapse bucket for names arriving past the registry cap: spend
# and books stay conserved (everything is counted SOMEWHERE), only the
# per-name breakdown saturates
OVERFLOW_TENANT = "__other__"

HEADER = "X-Tenant"

DEFAULT_MAX_TENANTS = int(os.environ.get("DL4J_MAX_TENANTS", "64"))

# label-value safety: tenant names land verbatim inside Prometheus-style
# label quotes and ledger JSONL — anything outside this set is mapped
# to "_" rather than trusted
_SAFE = set("abcdefghijklmnopqrstuvwxyz"
            "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.:/@")
_MAX_NAME_LEN = 64


def _sanitize(name: str) -> str:
    s = name.strip()[:_MAX_NAME_LEN]
    if not s:
        return DEFAULT_TENANT
    return "".join(ch if ch in _SAFE else "_" for ch in s)


class TenantRegistry:
    """Bounded process-global intern table. NOT an ACL — identity and
    accounting only; admission policy stays in the engines."""

    def __init__(self, max_tenants: int = DEFAULT_MAX_TENANTS):
        self.max_tenants = max(1, int(max_tenants))
        self._lock = threading.Lock()
        # insertion-ordered: first-come keeps its name, late arrivals
        # past the cap collapse — deterministic under replay
        self._known: Dict[str, bool] = {DEFAULT_TENANT: True}
        self.overflowed = 0

    def intern(self, name) -> str:
        """Canonical tenant label for `name`: None/empty -> the default
        tenant; a known name -> itself; a new name -> registered, or
        `__other__` once the cap is reached."""
        if name is None:
            return DEFAULT_TENANT
        s = _sanitize(str(name))
        if s in self._known or s == OVERFLOW_TENANT:
            return s
        with self._lock:
            if s in self._known:
                return s
            if len(self._known) >= self.max_tenants:
                self.overflowed += 1
                return OVERFLOW_TENANT
            self._known[s] = True
        return s

    def tenants(self) -> List[str]:
        with self._lock:
            return list(self._known)

    def reset(self, max_tenants: Optional[int] = None) -> None:
        """Tests only: drop every interned name (the process-global
        registry otherwise accumulates across a pytest session)."""
        with self._lock:
            self._known = {DEFAULT_TENANT: True}
            self.overflowed = 0
            if max_tenants is not None:
                self.max_tenants = max(1, int(max_tenants))


_REGISTRY = TenantRegistry()


def get_tenant_registry() -> TenantRegistry:
    return _REGISTRY


def intern(name) -> str:
    return _REGISTRY.intern(name)


# -- thread-local propagation -------------------------------------------------

_tls = threading.local()


def current_tenant() -> Optional[str]:
    """The tenant attached to THIS thread, or None — one thread-local
    read, the whole disabled-path cost of every metering hook."""
    return getattr(_tls, "tenant", None)


def attach(tenant: Optional[str]):
    """Make `tenant` the ambient identity on this thread (queue hops,
    HTTP handler threads). Returns the token for the paired detach().
    None attaches "no tenant" — symmetric, so handlers always pair."""
    prev = getattr(_tls, "tenant", None)
    _tls.tenant = intern(tenant) if tenant is not None else None
    return prev


def detach(token) -> None:
    _tls.tenant = token


class tenant_scope:
    """`with tenancy.tenant_scope("acme"): ...` — attach/detach pair as
    a context manager (the fit loop and benches use it)."""

    def __init__(self, tenant: Optional[str]):
        self._tenant = tenant
        self._tok = None

    def __enter__(self):
        self._tok = attach(self._tenant)
        return self

    def __exit__(self, *exc):
        detach(self._tok)
        return False


# -- header plumbing ----------------------------------------------------------

def from_headers(headers) -> Optional[str]:
    """The `X-Tenant` value from a header mapping, case-insensitively
    (HTTP/2 proxies lowercase header names), or None. Accepts both the
    email.Message-style mapping jsonhttp handlers see and a plain
    dict."""
    if headers is None:
        return None
    get = getattr(headers, "get", None)
    if get is not None:
        v = get(HEADER)
        if v is not None:
            return str(v)
    return next((str(v) for k, v in headers.items()
                 if k.lower() == "x-tenant"), None)


def tenant_headers(headers: Optional[dict] = None,
                   tenant: Optional[str] = None) -> dict:
    """Outbound header dict with the tenant injected as `X-Tenant` —
    the client half of cross-process propagation, the shape of
    jsonhttp.traced_headers. Explicit `tenant` wins over the ambient
    one; neither -> headers pass through untagged. Never mutates the
    input."""
    out = dict(headers) if headers else {}
    t = tenant if tenant is not None else current_tenant()
    if t is not None:
        out[HEADER] = intern(t)
    return out
