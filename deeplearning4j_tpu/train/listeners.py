"""Training listeners.

Analog of the reference's IterationListener/TrainingListener SPI
(optimize/api/, optimize/listeners/): ScoreIterationListener,
PerformanceListener (samples/sec + ETL time), CollectScoresIterationListener,
EvaluativeListener. The listener callback receives a small info dict; score
is fetched as a host scalar only when a listener actually wants it, so
listeners do not force device syncs on every step.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class IterationListener:
    """SPI (reference: optimize/api/IterationListener.java)."""

    def iteration_done(self, model, iteration: int, info: dict) -> None:
        raise NotImplementedError

    def on_epoch_start(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass

    def on_fit_end(self, model) -> None:
        """Called when the fit loop exits — INCLUDING on an exception
        (netbase runs it in a finally). The hook for restoring any
        process-global state a listener flipped for the run."""
        pass


class ScoreIterationListener(IterationListener):
    """Log the score every `frequency` iterations (reference:
    optimize/listeners/ScoreIterationListener.java)."""

    def __init__(self, frequency: int = 10, print_fn: Optional[Callable] = None):
        self.frequency = max(1, frequency)
        self.print_fn = print_fn or (lambda s: logger.info(s))

    def iteration_done(self, model, iteration, info):
        if iteration % self.frequency == 0:
            score = float(info["score"]())
            self.print_fn(f"Score at iteration {iteration} is {score}")


class PerformanceListener(IterationListener):
    """Throughput listener (reference: PerformanceListener.java — iterations
    /sec, samples/sec, ETL time)."""

    def __init__(self, frequency: int = 10, print_fn: Optional[Callable] = None):
        self.frequency = max(1, frequency)
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self._last_time = None
        self._samples = 0
        self._iters = 0
        self._etl_ms = 0.0
        self._fit_examples = None  # registry child, resolved lazily
        self._win_examples0 = None  # counter value at window start

    def iteration_done(self, model, iteration, info):
        now = time.perf_counter()
        self._samples += info.get("batch_size", 0)
        self._iters += 1
        # accumulate the fit loop's per-batch data-wait measurement so the
        # printed ETL is the window's average, not whatever the last batch
        # happened to block for (reference: PerformanceListener.java
        # reports real ETL time per window)
        self._etl_ms += info.get("etl_ms", 0.0)
        if self._last_time is None:
            self._last_time = now
            self._win_examples0 = self._fit_examples_total()
            return
        if self._iters % self.frequency == 0:
            dt = now - self._last_time
            if dt > 0:
                msg = (
                    f"iter {iteration}: {self._iters / dt:.1f} it/s, "
                    f"{self._samples / dt:.1f} samples/s, "
                    f"etl {self._etl_ms / self._iters:.1f} ms/iter"
                )
                mfu = self._window_mfu(model, dt)
                if mfu is not None:
                    msg += f", mfu {mfu:.3f}"
                self.print_fn(msg)
            self._last_time = now
            self._samples = 0
            self._iters = 0
            self._etl_ms = 0.0
            self._win_examples0 = self._fit_examples_total()

    def _fit_examples_total(self):
        """The fit loop's own once-per-batch example counter — NOT the
        per-callback tally: TBPTT fires iteration_done once per segment
        with the full batch size, so `self._samples` over-counts by the
        segment count and must never feed the MFU arithmetic. (The
        counter is process-global: a second net fitting concurrently in
        the same process would inflate this window's MFU.)"""
        try:
            from deeplearning4j_tpu.utils.metrics import get_registry

            child = self._fit_examples
            if child is None:
                child = self._fit_examples = get_registry().counter(
                    "fit_examples_total").labels()
            return child.value
        except Exception:
            return None

    def _window_mfu(self, model, dt: float):
        """Window-averaged MFU from the net's model FLOPs (jaxpr cost
        model when one is attached, analytic estimate otherwise — the
        same accounting as utils/devprof's step_mfu gauge). Only on
        device backends: chip-peak MFU against a CPU host is noise."""
        per_example = getattr(model, "model_flops_per_example", None)
        if per_example is None or self._win_examples0 is None:
            return None
        try:
            import jax

            if jax.default_backend() == "cpu":
                return None
            flops, _ = per_example()
            if not flops:
                return None
            examples = self._fit_examples_total()
            if examples is None:
                return None
            from deeplearning4j_tpu.utils.flops import peak_flops_per_chip

            return ((examples - self._win_examples0) * flops / dt
                    / peak_flops_per_chip())
        except Exception:
            return None


class CollectScoresIterationListener(IterationListener):
    """Accumulate (iteration, score) pairs (reference:
    CollectScoresIterationListener.java)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, info):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(info["score"]())))


class EvaluativeListener(IterationListener):
    """Periodically evaluate on a held-out set (reference:
    EvaluativeListener.java)."""

    def __init__(self, data_iterator, frequency: int = 100, print_fn=None):
        self.iterator = data_iterator
        self.frequency = max(1, frequency)
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self.last_evaluation = None

    def iteration_done(self, model, iteration, info):
        if iteration > 0 and iteration % self.frequency == 0:
            ev = model.evaluate(self.iterator)
            self.last_evaluation = ev
            self.print_fn(f"iter {iteration}: accuracy={ev.accuracy():.4f}")


class TracingListener(IterationListener):
    """Turn on the host-side span tracer for a training run and export
    the buffer at epoch ends — training jobs get the same span
    visibility as serving (`InferenceServer GET /trace`), through the
    listener SPI instead of an HTTP route.

    With tracing enabled, the fit loop itself emits the `fit/step` /
    `fit/dispatch` / `fit/device_sync` spans (nn/netbase.py); this
    listener adds an `iteration` instant per step (iteration number +
    batch size) and writes `jsonl_path` / `chrome_path` after each epoch
    so a killed run still leaves a trace artifact behind.

    Tracing is enabled at each epoch start and restored to its prior
    state at each epoch end (pass restore_on_epoch_end=False to leave it
    on between/after epochs). Construction alone changes nothing — the
    tracing flag is process-global and flipping it permanently would
    impose the per-step device sync on every OTHER net in the process."""

    def __init__(self, jsonl_path: Optional[str] = None,
                 chrome_path: Optional[str] = None,
                 restore_on_epoch_end: bool = True):
        from deeplearning4j_tpu.utils import tracing

        self._tracing = tracing
        self.jsonl_path = jsonl_path
        self.chrome_path = chrome_path
        self._restore = restore_on_epoch_end
        self._was_enabled: Optional[bool] = None

    def iteration_done(self, model, iteration, info):
        self._tracing.instant("iteration", iteration=iteration,
                              batch_size=info.get("batch_size"))

    def on_epoch_start(self, model, epoch):
        if self._was_enabled is None:  # prior state, captured at run start
            self._was_enabled = self._tracing.is_enabled()
        self._tracing.enable(True)

    def on_epoch_end(self, model, epoch):
        tracer = self._tracing.get_tracer()
        if self.jsonl_path:
            tracer.write_jsonl(self.jsonl_path)
        if self.chrome_path:
            tracer.write_chrome_trace(self.chrome_path)
        if self._restore:
            self._tracing.enable(bool(self._was_enabled))

    def on_fit_end(self, model):
        # runs in the fit loop's finally: a fit that raises mid-epoch
        # must still restore the process-global flag (and leave the
        # artifacts covering what WAS captured) — otherwise every other
        # net in the process inherits per-step device syncs forever
        if self._was_enabled is None:
            return  # fit never started an epoch
        if self.jsonl_path:
            self._tracing.get_tracer().write_jsonl(self.jsonl_path)
        if self.chrome_path:
            self._tracing.get_tracer().write_chrome_trace(self.chrome_path)
        if self._restore:
            self._tracing.enable(bool(self._was_enabled))


class HealthTransitionListener(IterationListener):
    """Forward watchdog health transitions (utils/health — component
    degraded/recovered events) into the stats-storage path, so the UI
    layer sees degradation HISTORY, not just the current
    `component_health` gauge value.

    Cursor-based: each `iteration_done` drains transitions newer than
    the last seen sequence number and routes them as one update record
    (`{"health_transitions": [...]}`) through the same
    StatsStorageRouter StatsListener uses; `on_fit_end` drains once more
    so a transition during the final partial window still lands. With no
    router it degrades to the package logger — degradations are never
    silent."""

    def __init__(self, router=None, session_id: Optional[str] = None):
        import uuid

        from deeplearning4j_tpu.utils.health import get_health

        self._health = get_health()
        self.router = router
        self.session_id = session_id or f"session-{uuid.uuid4().hex[:8]}"
        # start the cursor NOW: transitions from before this run belong
        # to whatever run recorded them
        self._seq = self._health.last_seq()

    def _drain(self, iteration: int):
        new = self._health.transitions_since(self._seq)
        if not new:
            return
        self._seq = max(t["seq"] for t in new)
        if self.router is not None:
            from deeplearning4j_tpu.utils.health import LEVELS

            # health_level carries the numeric end-state per component:
            # the binary stats codec (ui/codec) drops string leaves, so
            # the component-keyed numeric map is what survives
            # FileStatsStorage/remote routing; the raw transition dicts
            # ride along for in-memory/dashboard consumers
            self.router.put_update(self.session_id, {
                "iteration": int(iteration),
                "ts": time.time(),
                "health_transitions": new,
                "health_level": {t["component"]: LEVELS[t["to"]]
                                 for t in new},
            })
        for t in new:
            logger.info("health: %s %s -> %s (stalled %.3fs)",
                        t["component"], t["from"], t["to"],
                        t["stalled_for_seconds"])

    def iteration_done(self, model, iteration, info):
        self._drain(iteration)

    def on_fit_end(self, model):
        self._drain(getattr(model, "iteration", 0))


class ComposableIterationListener(IterationListener):
    def __init__(self, *listeners):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration, info):
        for listener in self.listeners:
            listener.iteration_done(model, iteration, info)
