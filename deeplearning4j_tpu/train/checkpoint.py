"""Preemption-aware checkpointing.

Reference baseline: ModelSerializer zips + early-stopping savers, all
manual — SURVEY §5 calls elastic/preemption handling "absent...
greenfield for the TPU build". TPU-idiomatic answer: periodic
checkpointing as a LISTENER on the existing SPI plus a preemption signal
hook, because TPU pools reclaim VMs with a SIGTERM grace window; a run
that saves on SIGTERM and resumes from the newest checkpoint loses at
most one save interval.

    listener = CheckpointListener("ckpts/", every_n_iterations=500,
                                  keep_last=3, save_on_preemption=True)
    net.set_listeners(listener)
    ...
    net2, meta = CheckpointListener.restore_latest("ckpts/")
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from typing import Optional, Tuple

from deeplearning4j_tpu.train.listeners import IterationListener
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import tracing as _tracing

logger = logging.getLogger("deeplearning4j_tpu")


class CheckpointListener(IterationListener):
    """Periodic + preemption-triggered model saves with retention.

    every_n_iterations / every_n_epochs / every_n_seconds: any
    combination; a save fires when any schedule is due.
    keep_last: retain the newest N checkpoints (0 = keep all).
    save_on_preemption: install a SIGTERM handler that saves
    synchronously before re-raising the default handler (the TPU/GCE
    preemption contract)."""

    def __init__(self, directory: str, *,
                 every_n_iterations: Optional[int] = None,
                 every_n_epochs: Optional[int] = 1,
                 every_n_seconds: Optional[float] = None,
                 keep_last: int = 3,
                 save_updater: bool = True,
                 save_on_preemption: bool = False):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.every_iter = every_n_iterations
        self.every_epoch = every_n_epochs
        self.every_seconds = every_n_seconds
        self.keep_last = int(keep_last)
        self.save_updater = save_updater
        self._last_time = time.monotonic()
        self._model = None
        self._lock = threading.Lock()
        self._prev_sigterm = None
        if save_on_preemption:
            self._install_preemption_hook()

    # -- listener hooks -------------------------------------------------------

    def iteration_done(self, model, iteration, info):
        self._model = model
        due = (self.every_iter is not None and iteration > 0
               and iteration % self.every_iter == 0)
        if (not due and self.every_seconds is not None
                and time.monotonic() - self._last_time >= self.every_seconds):
            due = True
        if due:
            self.save(model, reason="schedule")

    def on_epoch_end(self, model, epoch):
        self._model = model
        if self.every_epoch is not None and (epoch + 1) % self.every_epoch == 0:
            self.save(model, reason="epoch")

    # -- saving ---------------------------------------------------------------

    def save(self, model, reason: str = "manual",
             blocking: bool = True) -> Optional[str]:
        """blocking=False (the SIGTERM handler) skips instead of waiting:
        if a save is already mid-write on this thread, re-entering would
        corrupt it — and its result is at most one interval stale."""
        from deeplearning4j_tpu.utils.model_serializer import save_model

        if not self._lock.acquire(blocking=blocking):
            logger.warning("checkpoint save already in flight; skipping "
                           "(%s)", reason)
            return None
        t0 = time.perf_counter()
        try:
            name = f"checkpoint_iter{model.iteration:09d}.zip"
            path = os.path.join(self.dir, name)
            tmp = f"{path}.{os.getpid()}.{reason}.tmp"  # unique per writer
            with _tracing.span("checkpoint/save", reason=reason):
                save_model(model, tmp, save_updater=self.save_updater)
                os.replace(tmp, path)  # atomic: never a torn checkpoint
            reg = _metrics.get_registry()
            reg.counter("checkpoint_saves_total", "checkpoints written",
                        ("reason",)).labels(reason).inc()
            reg.histogram("checkpoint_save_seconds",
                          "checkpoint save duration (serialize + atomic "
                          "rename)").observe(time.perf_counter() - t0)
            meta = {
                "iteration": int(model.iteration),
                "epoch": int(model.epoch),
                "ts": time.time(),
                "reason": reason,
                "file": name,
            }
            with open(os.path.join(self.dir, "latest.json"), "w") as f:
                json.dump(meta, f)
            self._last_time = time.monotonic()
            self._gc()
            logger.info("checkpoint saved: %s (%s)", path, reason)
            return path
        finally:
            self._lock.release()

    def _gc(self):
        # orphaned temp files from writers killed mid-save. A tmp file is
        # only an orphan if its embedded pid is not a live process (several
        # hosts may share the dir) AND it hasn't been touched recently —
        # deleting a peer's in-flight write would corrupt its save.
        now = time.time()
        for f in os.listdir(self.dir):
            if ".tmp" in f and f.startswith("checkpoint_iter"):
                path = os.path.join(self.dir, f)
                try:
                    pid = int(f.split(".")[-3])
                except (ValueError, IndexError):
                    pid = None
                if pid is not None and pid != os.getpid():
                    try:
                        os.kill(pid, 0)  # 0 = existence probe, no signal
                        continue  # writer is alive: leave its tmp alone
                    except ProcessLookupError:
                        pass  # dead pid: orphan
                    except OSError:
                        continue  # EPERM etc: play safe, keep the file
                try:
                    if now - os.path.getmtime(path) < 300:
                        continue  # written moments ago: grace window
                    os.remove(path)
                except OSError:
                    pass
        if self.keep_last <= 0:
            return
        ckpts = sorted(
            f for f in os.listdir(self.dir)
            if f.startswith("checkpoint_iter") and f.endswith(".zip"))
        for stale in ckpts[:-self.keep_last]:
            try:
                os.remove(os.path.join(self.dir, stale))
            except OSError:
                pass

    # -- preemption -----------------------------------------------------------

    def _install_preemption_hook(self):
        if threading.current_thread() is not threading.main_thread():
            logger.warning("preemption hook requires the main thread; "
                           "skipping signal installation")
            return

        def handler(signum, frame):
            model = self._model
            if model is not None:
                try:
                    self.save(model, reason="preemption", blocking=False)
                except Exception:
                    logger.exception("preemption save failed")
            if callable(self._prev_sigterm):
                self._prev_sigterm(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        self._prev_sigterm = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, handler)

    # -- resume ---------------------------------------------------------------

    @staticmethod
    def restore_latest(directory: str,
                       load_updater: bool = True) -> Tuple[object, dict]:
        """(model, meta) from the newest checkpoint in `directory`.
        Raises FileNotFoundError when none exists (fresh start)."""
        from deeplearning4j_tpu.utils.model_serializer import load_model

        meta_path = os.path.join(directory, "latest.json")
        if not os.path.exists(meta_path):
            raise FileNotFoundError(f"no checkpoint in {directory!r}")
        with open(meta_path) as f:
            meta = json.load(f)
        t0 = time.perf_counter()
        with _tracing.span("checkpoint/load", file=meta.get("file")):
            model = load_model(os.path.join(directory, meta["file"]),
                               load_updater=load_updater)
        _metrics.get_registry().histogram(
            "checkpoint_load_seconds",
            "checkpoint restore duration").observe(time.perf_counter() - t0)
        return model, meta
