"""Graph embeddings (reference: deeplearning4j-graph, 3,363 LoC —
IGraph/Graph, random-walk iterators, DeepWalk + GraphHuffman +
InMemoryGraphLookupTable, GraphVectors serving API)."""

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.deepwalk import DeepWalk, GraphVectors
from deeplearning4j_tpu.graph.walkers import RandomWalkIterator

__all__ = ["Graph", "DeepWalk", "GraphVectors", "RandomWalkIterator"]
