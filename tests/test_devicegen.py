"""Device-side skip-gram example generation (nlp/devicegen.py): pair
extraction invariants vs a brute-force oracle, sentence-boundary safety,
and end-to-end learning through the corpus-resident train path (which
the skipgram+negative-sampling configuration now uses by default)."""

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nlp.devicegen import (
    SENTINEL,
    corpus_pairs_debug,
    pack_corpus,
)
from deeplearning4j_tpu.nlp.sequencevectors import (
    SequenceVectors,
    VectorsConfiguration,
)


def test_pack_corpus_gaps_and_padding():
    sents = [np.array([1, 2, 3]), np.array([], np.int64), np.array([4, 5])]
    out = pack_corpus(sents, window=3, bucket=16)
    assert out.size == 16
    np.testing.assert_array_equal(
        out[:11],
        [1, 2, 3, SENTINEL, SENTINEL, SENTINEL, 4, 5,
         SENTINEL, SENTINEL, SENTINEL])
    assert (out[11:] == SENTINEL).all()


def _brute_pairs(corpus, window):
    """Oracle: ALL same-sentence (input=context, target=center) pairs
    within `window` (the superset any dynamic-window draw can emit)."""
    pairs = set()
    n = corpus.size
    for i in range(n):
        if corpus[i] < 0:
            continue
        for d in range(1, window + 1):
            for j in (i - d, i + d):
                if 0 <= j < n and corpus[j] >= 0:
                    pairs.add((int(corpus[j]), int(corpus[i]), d))
    return pairs


def test_device_pairs_subset_of_oracle_and_d1_complete():
    rng = np.random.default_rng(0)
    sents = [rng.integers(1, 50, rng.integers(2, 12)).astype(np.int64)
             for _ in range(8)]
    window = 4
    corpus = pack_corpus(sents, window, bucket=64)
    ins, tgt, valid = corpus_pairs_debug(
        corpus, window, jax.random.PRNGKey(7))
    oracle = _brute_pairs(corpus, window)
    oracle_it = {(a, b) for a, b, _ in oracle}

    n_centers = corpus.size
    offsets = np.concatenate([np.arange(-window, 0),
                              np.arange(1, window + 1)])
    dist = np.abs(np.tile(offsets, n_centers))
    got = list(zip(ins[valid], tgt[valid]))
    assert got, "no pairs generated"
    # every generated pair exists in the oracle (no cross-sentence or
    # sentinel leakage, correct input/target roles)
    for pair in got:
        assert (int(pair[0]), int(pair[1])) in oracle_it
    # distance-1 pairs are ALWAYS valid (w_eff = window - b >= 1), so the
    # full oracle set at d=1 must be present
    d1_got = {(int(a), int(b)) for (a, b), d in
              zip(zip(ins, tgt), dist) if d == 1}
    d1_oracle = {(a, b) for a, b, d in oracle if d == 1}
    # restrict the generated side to valid rows
    d1_got_valid = {(int(a), int(b)) for (a, b), d, v in
                    zip(zip(ins, tgt), dist, valid) if d == 1 and v}
    assert d1_oracle <= d1_got_valid


def test_no_pairs_cross_sentence_boundaries():
    # two sentences of distinct vocab ranges: no mixed pair may appear
    sents = [np.arange(1, 8), np.arange(100, 108)]
    window = 5
    corpus = pack_corpus(sents, window, bucket=64)
    ins, tgt, valid = corpus_pairs_debug(
        corpus, window, jax.random.PRNGKey(3))
    for a, b in zip(ins[valid], tgt[valid]):
        assert (a < 50) == (b < 50), f"cross-sentence pair {a}->{b}"


def _cluster_corpus(n=300, seed=5):
    """Two disjoint topic clusters (mirrors test_word2vec patterns)."""
    rng = np.random.default_rng(seed)
    a = ["apple", "banana", "cherry", "grape"]
    b = ["cpu", "gpu", "ram", "disk"]
    sents = []
    for _ in range(n):
        pool = a if rng.random() < 0.5 else b
        sents.append([pool[i] for i in rng.integers(0, len(pool), 6)])
    return sents, a, b


def test_corpus_device_path_learns_clusters():
    sents, a, b = _cluster_corpus()
    conf = VectorsConfiguration(
        layer_size=24, window=3, min_word_frequency=1, epochs=12,
        negative=5, use_hierarchic_softmax=False, batch_size=1024,
        learning_rate=0.05, seed=11)
    sv = SequenceVectors(conf, sents)
    sv.fit()
    assert np.isfinite(sv.last_loss)
    within = sv.similarity(a[0], a[1])
    across = sv.similarity(a[0], b[0])
    assert within > across, (within, across)


def test_corpus_device_path_is_selected(monkeypatch):
    """skipgram + negative sampling must route through the corpus path,
    not the host pair-batch path."""
    sents, _, _ = _cluster_corpus(50)
    conf = VectorsConfiguration(
        layer_size=8, window=2, min_word_frequency=1, epochs=1,
        negative=3, use_hierarchic_softmax=False, batch_size=256)
    sv = SequenceVectors(conf, sents)
    called = {}
    orig = sv._train_corpus_device
    monkeypatch.setattr(
        sv, "_train_corpus_device",
        lambda idx: called.setdefault("yes", True) or orig(idx))
    sv.fit()
    assert called.get("yes")


def test_hs_path_still_uses_batched(monkeypatch):
    sents, _, _ = _cluster_corpus(50)
    conf = VectorsConfiguration(
        layer_size=8, window=2, min_word_frequency=1, epochs=1,
        negative=0, use_hierarchic_softmax=True, batch_size=256)
    sv = SequenceVectors(conf, sents)
    monkeypatch.setattr(
        sv, "_train_corpus_device",
        lambda idx: (_ for _ in ()).throw(AssertionError("wrong path")))
    sv.fit()  # must not raise
    assert np.isfinite(sv.last_loss)
