"""Early stopping + transfer learning tests (reference patterns:
earlystopping/trainer/BaseEarlyStoppingTrainer tests and
TransferLearning builder tests in deeplearning4j-core)."""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.conf.layers import FrozenLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import (
    TransferLearning,
    TransferLearningHelper,
)
from deeplearning4j_tpu.train.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    TerminationReason,
)


def _net(lr=0.05, seed=7):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Updater.ADAM)
        .learning_rate(lr)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
        .layer(DenseLayer(n_in=16, n_out=12, activation="tanh"))
        .layer(OutputLayer(n_in=12, n_out=3, activation="softmax", loss="mcxent"))
        .build()
    ).init()


def _xy(n=64, nin=8, nout=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, nin)).astype(np.float32)
    y = np.zeros((n, nout), np.float32)
    y[np.arange(n), rng.integers(0, nout, n)] = 1.0
    return x, y


# -- early stopping ----------------------------------------------------------

def test_max_epochs_condition():
    x, y = _xy()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(DataSet(x, y)),
        epoch_termination_conditions=[MaxEpochsTerminationCondition(5)],
    )
    result = EarlyStoppingTrainer(cfg, _net(), x, y, batch_size=32).fit()
    assert result.termination_reason == TerminationReason.EPOCH_CONDITION
    assert "MaxEpochs" in result.termination_details
    assert result.total_epochs == 5
    assert result.best_model is not None
    assert np.isfinite(result.best_model_score)


def test_score_improvement_patience():
    """With an absurd min_improvement, patience triggers quickly."""
    x, y = _xy()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(DataSet(x, y)),
        epoch_termination_conditions=[
            ScoreImprovementEpochTerminationCondition(2, min_improvement=100.0),
            MaxEpochsTerminationCondition(50),
        ],
    )
    result = EarlyStoppingTrainer(cfg, _net(), x, y, batch_size=32).fit()
    assert "ScoreImprovement" in result.termination_details
    assert result.total_epochs <= 5


def test_max_score_iteration_condition_aborts():
    """A divergence bound below the initial loss aborts inside epoch 0."""
    x, y = _xy()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(DataSet(x, y)),
        epoch_termination_conditions=[MaxEpochsTerminationCondition(10)],
        iteration_termination_conditions=[
            MaxScoreIterationTerminationCondition(1e-9),
        ],
    )
    result = EarlyStoppingTrainer(cfg, _net(), x, y, batch_size=32).fit()
    assert result.termination_reason == TerminationReason.ITERATION_CONDITION
    assert "MaxScore" in result.termination_details


def test_max_time_condition_aborts():
    x, y = _xy()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(DataSet(x, y)),
        epoch_termination_conditions=[MaxEpochsTerminationCondition(10_000)],
        iteration_termination_conditions=[
            MaxTimeIterationTerminationCondition(0.0),
        ],
    )
    result = EarlyStoppingTrainer(cfg, _net(), x, y, batch_size=32).fit()
    assert result.termination_reason == TerminationReason.ITERATION_CONDITION


def test_best_model_tracked_and_usable():
    x, y = _xy()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(DataSet(x, y)),
        epoch_termination_conditions=[MaxEpochsTerminationCondition(8)],
        model_saver=InMemoryModelSaver(),
    )
    result = EarlyStoppingTrainer(cfg, _net(), x, y, batch_size=32).fit()
    best = result.best_model
    assert best.score(x, y) == pytest.approx(result.best_model_score, rel=1e-4)
    assert min(result.score_vs_epoch.values()) == result.best_model_score


def test_local_file_saver(tmp_path):
    x, y = _xy()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(DataSet(x, y)),
        epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
        model_saver=LocalFileModelSaver(str(tmp_path)),
        save_last_model=True,
    )
    result = EarlyStoppingTrainer(cfg, _net(), x, y, batch_size=32).fit()
    assert (tmp_path / "bestModel.zip").exists()
    assert (tmp_path / "latestModel.zip").exists()
    loaded = cfg.model_saver.get_best_model()
    assert loaded.score(x, y) == pytest.approx(result.best_model_score, rel=1e-4)


def test_invalid_score_condition():
    c = InvalidScoreIterationTerminationCondition()
    assert c.terminate(0, float("nan"))
    assert c.terminate(0, float("inf"))
    assert not c.terminate(0, 1.0)


# -- transfer learning -------------------------------------------------------

def test_set_feature_extractor_freezes():
    x, y = _xy()
    src = _net()
    src.fit(x, y, epochs=2, batch_size=32, async_prefetch=False)
    new = (
        TransferLearning.Builder(src)
        .set_feature_extractor(1)
        .build()
    )
    assert isinstance(new.layer_confs[0], FrozenLayer)
    assert isinstance(new.layer_confs[1], FrozenLayer)
    assert not isinstance(new.layer_confs[2], FrozenLayer)
    frozen_before = [np.asarray(p["W"]).copy() for p in new.params_list[:2]]
    head_before = np.asarray(new.params_list[2]["W"]).copy()
    new.fit(x, y, epochs=3, batch_size=32, async_prefetch=False)
    for before, p in zip(frozen_before, new.params_list[:2]):
        np.testing.assert_array_equal(before, np.asarray(p["W"]))
    assert np.abs(head_before - np.asarray(new.params_list[2]["W"])).max() > 0
    # source network untouched (functional builder)
    assert not isinstance(src.layer_confs[0], FrozenLayer)


def test_n_out_replace_rewires_and_transfers():
    x, y = _xy()
    src = _net()
    src.fit(x, y, epochs=2, batch_size=32, async_prefetch=False)
    new = (
        TransferLearning.Builder(src)
        .n_out_replace(1, 20, weight_init="xavier")
        .build()
    )
    assert new.layer_confs[1].n_out == 20
    assert new.layer_confs[2].n_in == 20
    assert new.params_list[1]["W"].shape == (16, 20)
    assert new.params_list[2]["W"].shape == (20, 3)
    # untouched layer 0 shares the trained weights
    np.testing.assert_array_equal(
        np.asarray(src.params_list[0]["W"]), np.asarray(new.params_list[0]["W"])
    )
    new.fit(x, y, epochs=1, batch_size=32, async_prefetch=False)


def test_remove_and_add_output_layer():
    src = _net()
    new = (
        TransferLearning.Builder(src)
        .remove_output_layer()
        .add_layer(DenseLayer(n_out=10, activation="relu"))
        .add_layer(OutputLayer(n_out=5, activation="softmax", loss="mcxent"))
        .build()
    )
    assert len(new.layer_confs) == 4
    assert new.layer_confs[2].n_in == 12  # wired from previous layer
    assert new.layer_confs[3].n_in == 10
    x, _ = _xy()
    assert new.output(x).shape == (64, 5)


def test_fine_tune_configuration_overrides():
    src = _net(lr=0.05)
    new = (
        TransferLearning.Builder(src)
        .fine_tune_configuration(learning_rate=0.001, updater="sgd")
        .build()
    )
    assert new.net_conf.learning_rate == 0.001
    assert new.net_conf.updater == "sgd"
    with pytest.raises(ValueError, match="unknown fine-tune"):
        TransferLearning.Builder(src).fine_tune_configuration(bogus=1).build()


def test_freeze_then_finetune_accuracy():
    """The reference's canonical flow: pretrain on task A, freeze the
    trunk, fine-tune a new head on task B — accuracy on B improves."""
    xa, ya = _xy(128, seed=1)
    src = _net()
    src.fit(xa, ya, epochs=8, batch_size=32, async_prefetch=False)

    xb, yb = _xy(128, nout=3, seed=99)
    new = (
        TransferLearning.Builder(src)
        .set_feature_extractor(1)
        .n_out_replace(2, 3, weight_init="xavier")
        .fine_tune_configuration(learning_rate=0.01)
        .build()
    )
    acc0 = new.evaluate(xb, yb).accuracy()
    new.fit(xb, yb, epochs=25, batch_size=32, async_prefetch=False)
    acc1 = new.evaluate(xb, yb).accuracy()
    assert acc1 > acc0


def test_transfer_learning_helper_featurize():
    x, y = _xy(64)
    src = _net()
    src.fit(x, y, epochs=2, batch_size=32, async_prefetch=False)
    frozen = TransferLearning.Builder(src).set_feature_extractor(1).build()
    helper = TransferLearningHelper(frozen)
    feat = helper.featurize(DataSet(x, y))
    assert feat.features.shape == (64, 12)  # output of layer 1
    # training on featurized data == training the tail; outputs must match
    # the full network's on the same params
    helper.fit_featurized(feat.features, feat.labels, epochs=3, batch_size=32)
    full_out = np.asarray(frozen.output(x))
    tail_out = np.asarray(helper.unfrozen_network().output(feat.features))
    np.testing.assert_allclose(full_out, tail_out, rtol=1e-5, atol=1e-6)
