"""Scale-out: data, tensor, sequence and pipeline parallelism.

TPU-native replacement for deeplearning4j-scaleout (SURVEY.md §2.4): the
reference's three data-parallel transports (thread-replica ParallelWrapper,
Aeron parameter server, Spark parameter averaging) collapse into one
data-parallel mechanism here — sharded global batches + XLA GSPMD gradient
allreduce over ICI/DCN on a `jax.sharding.Mesh` — and the package goes
beyond the reference with tensor parallelism (`tensor`), ring-attention
sequence parallelism (`sequence`), and GPipe pipeline parallelism
(`pipeline`), all composable on one mesh.
"""

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharded,
    data_parallel_mesh,
    data_shards,
    mesh_2d,
    n_devices,
    replicated,
)
from deeplearning4j_tpu.parallel.sharded import MeshPlan, auto_mesh_enabled
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.parallel.inference import (
    DeadlineExceeded,
    InferenceMode,
    ParallelInference,
    ReplicaPool,
    RequestRejected,
    RequestValidationError,
    power_of_two_buckets,
)
from deeplearning4j_tpu.parallel.tensor import shard_params_tp, tp_dense_specs
from deeplearning4j_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_parallel_mesh,
    shard_stage_params,
)

__all__ = [
    "pipeline_apply",
    "pipeline_parallel_mesh",
    "shard_stage_params",
    "DATA_AXIS",
    "MODEL_AXIS",
    "batch_sharded",
    "data_parallel_mesh",
    "data_shards",
    "mesh_2d",
    "n_devices",
    "replicated",
    "MeshPlan",
    "auto_mesh_enabled",
    "ParallelWrapper",
    "ParallelInference",
    "ReplicaPool",
    "InferenceMode",
    "RequestValidationError",
    "RequestRejected",
    "DeadlineExceeded",
    "power_of_two_buckets",
]
