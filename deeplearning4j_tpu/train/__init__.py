"""Training stack: updaters, gradient normalization, listeners, evaluation,
early stopping, gradient checks.

Analog of the reference's optimize/ + nn/updater/ + eval/ + earlystopping/
subsystems (SURVEY.md §2.1), collapsed into pure functions that live inside
one jitted train step instead of a Solver/Updater object graph.
"""
