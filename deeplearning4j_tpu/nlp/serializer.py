"""WordVectorSerializer — embeddings interop.

Analog of the reference's models/embeddings/loader/WordVectorSerializer
.java (2,820 LoC): the Google word2vec binary and text formats (the
industry interchange formats, reference loadGoogleModel :112-154), plus a
full-model zip that round-trips vocab counts and the HS/negative output
tables so training can resume.
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
from typing import Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.sequencevectors import (
    SequenceVectors,
    VectorsConfiguration,
)
from deeplearning4j_tpu.nlp.vocab import Huffman, VocabCache


class WordVectorSerializer:
    # -- Google text format --------------------------------------------------

    @staticmethod
    def write_word_vectors(model: SequenceVectors, path: str):
        """word2vec text format: one `word v1 v2 ...` line per word."""
        vecs = model.lookup.vectors()
        with open(path, "w", encoding="utf-8") as f:
            for i, word in enumerate(model.vocab.words()):
                vals = " ".join(f"{x:.6f}" for x in vecs[i])
                f.write(f"{word} {vals}\n")

    @staticmethod
    def read_word_vectors(path: str) -> SequenceVectors:
        words, rows = [], []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < 2:
                    continue
                words.append(parts[0])
                rows.append(np.asarray([float(x) for x in parts[1:]], np.float32))
        return WordVectorSerializer._from_vectors(words, np.stack(rows))

    # -- Google binary format ------------------------------------------------

    @staticmethod
    def write_google_binary(model: SequenceVectors, path: str):
        """Google word2vec .bin: header `V D\\n`, then per word
        `word<space>` + D little-endian f32 (reference: loadGoogleModel
        reads exactly this layout)."""
        vecs = model.lookup.vectors()
        V, D = vecs.shape
        with open(path, "wb") as f:
            f.write(f"{V} {D}\n".encode("utf-8"))
            for i, word in enumerate(model.vocab.words()):
                f.write(word.encode("utf-8") + b" ")
                f.write(vecs[i].astype("<f4").tobytes())
                f.write(b"\n")

    @staticmethod
    def read_google_binary(path: str) -> SequenceVectors:
        with open(path, "rb") as f:
            header = f.readline().decode("utf-8").strip().split()
            V, D = int(header[0]), int(header[1])
            words, rows = [], []
            for _ in range(V):
                chars = bytearray()
                while True:
                    ch = f.read(1)
                    if ch == b" " or ch == b"":
                        break
                    if ch != b"\n":
                        chars.extend(ch)
                words.append(chars.decode("utf-8"))
                rows.append(
                    np.frombuffer(f.read(4 * D), dtype="<f4").copy()
                )
                # optional trailing newline
                pos = f.tell()
                nxt = f.read(1)
                if nxt != b"\n":
                    f.seek(pos)
        return WordVectorSerializer._from_vectors(words, np.stack(rows))

    # -- full-model zip ------------------------------------------------------

    @staticmethod
    def write_full_model(model: SequenceVectors, path: str):
        """Zip: config.json + vocab.json + tables.npz (syn0/syn1/syn1neg)
        — the resume-training form (reference: writeFullModel)."""
        conf = model.conf
        vocab_entries = [
            {"word": w.word, "count": w.count}
            for w in model.vocab.vocab_words()
        ]
        arrays = {"syn0": model.lookup.vectors()}
        if model.lookup.syn1 is not None:
            arrays["syn1"] = np.asarray(model.lookup.syn1)
        if model.lookup.syn1neg is not None:
            arrays["syn1neg"] = np.asarray(model.lookup.syn1neg)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("config.json", json.dumps(dataclass_dict(conf)))
            zf.writestr("vocab.json", json.dumps(vocab_entries))
            zf.writestr("tables.npz", buf.getvalue())

    @staticmethod
    def read_full_model(path: str) -> SequenceVectors:
        with zipfile.ZipFile(path, "r") as zf:
            conf = VectorsConfiguration(**json.loads(zf.read("config.json")))
            vocab_entries = json.loads(zf.read("vocab.json"))
            with np.load(io.BytesIO(zf.read("tables.npz"))) as npz:
                arrays = {k: npz[k] for k in npz.files}
        vocab = VocabCache()
        for e in vocab_entries:
            vocab.add(e["word"], e["count"])
        model = SequenceVectors(conf, vocab=vocab)
        model.build_vocab()
        model.lookup.syn0 = jnp.asarray(arrays["syn0"])
        if "syn1" in arrays and model.lookup.syn1 is not None:
            model.lookup.syn1 = jnp.asarray(arrays["syn1"])
        if "syn1neg" in arrays and model.lookup.syn1neg is not None:
            model.lookup.syn1neg = jnp.asarray(arrays["syn1neg"])
        return model

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _from_vectors(words, vectors: np.ndarray) -> SequenceVectors:
        """Vectors-only model (inference/query use — reference:
        loadStaticModel)."""
        vocab = VocabCache()
        for w in words:
            vocab.add(w, 1)
        conf = VectorsConfiguration(
            layer_size=int(vectors.shape[1]), min_word_frequency=1,
            use_hierarchic_softmax=False, negative=0,
        )
        model = SequenceVectors(conf, vocab=vocab)
        model.lookup = InMemoryLookupTable(
            vocab, conf.layer_size, use_hs=False, negative=0,
        )
        model.lookup.set_vectors(vectors)
        return model


def dataclass_dict(conf: VectorsConfiguration) -> dict:
    import dataclasses

    return dataclasses.asdict(conf)
