"""Training dashboard HTTP server.

Reference: PlayUIServer (deeplearning4j-play, runnable with --uiPort) +
TrainModule route table (module/train/TrainModule.java:96-112):
/train -> overview, /train/overview(/data), /train/model(/graph,
/data/:layerId), /train/system(/data), /train/sessions/current|all; the
RemoteReceiverModule accepts stats POSTed from remote training processes.

Self-contained stdlib implementation: JSON data routes consumed by an
inline HTML/SVG dashboard (no external assets — the box it runs on may
have zero egress), polling /train/overview/data every 2s.
"""

from __future__ import annotations

import json
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse

from deeplearning4j_tpu.ui.codec import decode_record
from deeplearning4j_tpu.ui.storage import StatsStorage


_PAGE = """<!doctype html>
<html><head><title>dl4j-tpu training UI</title>
<style>
 body {{ font-family: sans-serif; margin: 1.5em; background: #fafafa; }}
 h1 {{ font-size: 1.2em; }} h2 {{ font-size: 1em; color: #444; }}
 .chart {{ background: #fff; border: 1px solid #ddd; margin: 0.6em;
           padding: 0.4em; display: inline-block; }}
 nav a {{ margin-right: 1.2em; }}
 table {{ border-collapse: collapse; }} td, th {{ border: 1px solid #ccc;
   padding: 2px 8px; font-size: 0.85em; }}
</style></head>
<body>
<nav><a href="/train/overview">overview</a><a href="/train/model">model</a>
<a href="/train/system">system</a></nav>
<h1>dl4j-tpu training — {title}</h1>
<div id="content">loading…</div>
<script>
const VIEW = "{view}";
function line(points, w, h, color) {{
  if (points.length < 2) return "<svg width="+w+" height="+h+"></svg>";
  const xs = points.map(p => p[0]), ys = points.map(p => p[1]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs);
  const y0 = Math.min(...ys), y1 = Math.max(...ys);
  const sx = x => 4 + (w-8) * (x - x0) / Math.max(x1 - x0, 1e-9);
  const sy = y => h - 4 - (h-8) * (y - y0) / Math.max(y1 - y0, 1e-9);
  const d = points.map((p,i) => (i?"L":"M") + sx(p[0]).toFixed(1) + "," +
                                sy(p[1]).toFixed(1)).join(" ");
  return `<svg width=${{w}} height=${{h}}><path d="${{d}}" fill="none"
          stroke="${{color}}" stroke-width="1.5"/></svg>
          <div style="font-size:0.7em;color:#888">min ${{y0.toPrecision(4)}}
          max ${{y1.toPrecision(4)}}</div>`;
}}
function chart(title, pts, color) {{
  return `<div class="chart"><h2>${{title}}</h2>${{line(pts,380,160,color)}}</div>`;
}}
async function refresh() {{
  const r = await fetch("/train/" + VIEW + "/data");
  const d = await r.json();
  let html = "";
  if (VIEW == "overview") {{
    html += chart("score vs iteration", d.score, "#1565c0");
    html += chart("samples/sec", d.samples_per_sec, "#2e7d32");
    html += chart("update:param ratio (log10)", d.update_ratio, "#c62828");
    html += chart("etl ms", d.etl_ms, "#6a1b9a");
  }} else if (VIEW == "model") {{
    for (const layer of d.layers) {{
      html += `<h2>layer ${{layer.index}} — ${{layer.type}}
               (${{layer.n_params}} params)</h2>`;
      for (const [name, pts] of Object.entries(layer.series))
        html += chart(name, pts, "#00695c");
    }}
  }} else {{
    html += "<table><tr><th>key</th><th>value</th></tr>";
    for (const [k,v] of Object.entries(d.static || {{}}))
      html += `<tr><td>${{k}}</td><td>${{JSON.stringify(v)}}</td></tr>`;
    html += "</table>";
    for (const [dev, pts] of Object.entries(d.memory || {{}}))
      html += chart(dev + " bytes in use", pts, "#ef6c00");
  }}
  document.getElementById("content").innerHTML = html;
}}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class UIServer:
    """UIServer(storage, port=9090).start() -> bound port."""

    _instance = None

    def __init__(self, storage: StatsStorage, port: int = 9090):
        self.storage = storage
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def get_instance(cls, storage: Optional[StatsStorage] = None,
                     port: int = 9090) -> "UIServer":
        """Singleton accessor (reference: UIServer.getInstance())."""
        if cls._instance is None:
            from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

            cls._instance = cls(storage or InMemoryStatsStorage(), port)
            cls._instance.start()
        return cls._instance

    # -- data assembly -------------------------------------------------------

    def _current_session(self) -> Optional[str]:
        """Most recently ACTIVE session (latest update/static timestamp),
        not lexicographic order — random session-id suffixes don't sort
        by age."""
        ids = self.storage.list_session_ids()
        if not ids:
            return None

        def last_ts(sid):
            ups = self.storage.get_updates(sid)
            if ups:
                return ups[-1].get("ts", 0.0)
            st = self.storage.get_static_info(sid) or {}
            return st.get("start_time", 0.0)

        return max(ids, key=last_ts)

    def _overview_data(self, session: Optional[str]) -> dict:
        ups = self.storage.get_updates(session) if session else []
        import math

        def ratio(u):
            um, pm = u.get("update_mm"), u.get("param_mm")
            if not um or not pm:
                return None
            us = sum(um.values()) / max(len(um), 1)
            ps = sum(pm.values()) / max(len(pm), 1)
            if us <= 0 or ps <= 0:
                return None
            return math.log10(us / ps)

        return {
            "session": session,
            "score": [[u["iteration"], u["score"]] for u in ups],
            "samples_per_sec": [
                [u["iteration"], u["samples_per_sec"]] for u in ups],
            "etl_ms": [[u["iteration"], u["etl_ms"]] for u in ups],
            "update_ratio": [
                [u["iteration"], r] for u in ups
                if (r := ratio(u)) is not None],
        }

    def _model_data(self, session: Optional[str]) -> dict:
        ups = self.storage.get_updates(session) if session else []
        static = (self.storage.get_static_info(session) or {}) if session else {}
        layers = []
        for meta in static.get("layers", []):
            li = meta["index"]
            series = {}
            for group, label in (("grad_mm", "grad"), ("update_mm", "update"),
                                 ("param_mm", "param")):
                for u in ups:
                    g = u.get(group) or {}
                    for k, v in g.items():
                        if k.startswith(f"{li}_"):
                            series.setdefault(
                                f"{label} |{k[len(str(li)) + 1:]}|", []
                            ).append([u["iteration"], v])
            layers.append({**meta, "series": series})
        return {"session": session, "layers": layers}

    def _system_data(self, session: Optional[str]) -> dict:
        ups = self.storage.get_updates(session) if session else []
        static = (self.storage.get_static_info(session) or {}) if session else {}
        memory = {}
        for u in ups:
            for dev, m in (u.get("memory") or {}).items():
                memory.setdefault(dev, []).append(
                    [u["iteration"], m.get("bytes_in_use", 0)])
        return {"session": session, "static": static, "memory": memory}

    # -- http ----------------------------------------------------------------

    def start(self) -> int:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body: bytes, ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, obj, code=200):
                self._send(code, json.dumps(obj).encode())

            def do_GET(self):
                path = urlparse(self.path).path.rstrip("/") or "/train/overview"
                session = outer._current_session()
                if path in ("/train", "/train/overview"):
                    self._send(200, _PAGE.format(
                        title="overview", view="overview").encode(),
                        "text/html")
                elif path == "/train/model":
                    self._send(200, _PAGE.format(
                        title="model", view="model").encode(), "text/html")
                elif path == "/train/system":
                    self._send(200, _PAGE.format(
                        title="system", view="system").encode(), "text/html")
                elif path == "/train/overview/data":
                    self._json(outer._overview_data(session))
                elif path == "/train/model/data":
                    self._json(outer._model_data(session))
                elif path == "/train/model/graph":
                    st = (outer.storage.get_static_info(session) or {}
                          ) if session else {}
                    self._json({"layers": st.get("layers", [])})
                elif path == "/train/system/data":
                    self._json(outer._system_data(session))
                elif path == "/train/sessions/current":
                    self._json({"session": session})
                elif path == "/train/sessions/all":
                    self._json({"sessions": outer.storage.list_session_ids()})
                else:
                    self._json({"error": f"no route {path}"}, 404)

            def do_POST(self):
                # remote receiver (reference: RemoteReceiverModule)
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                session = self.headers.get("X-Session-Id", "remote")
                path = urlparse(self.path).path
                try:
                    if path == "/remote/static":
                        outer.storage.put_static_info(
                            session, json.loads(body))
                    elif path == "/remote/update":
                        outer.storage.put_update(
                            session, decode_record(body))
                    else:
                        return self._json({"error": "bad route"}, 404)
                    self._json({"status": "ok"})
                except (ValueError, KeyError, IndexError,
                        struct.error) as e:
                    self._json({"error": str(e)}, 400)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if UIServer._instance is self:
            UIServer._instance = None
