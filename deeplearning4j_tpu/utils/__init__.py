"""Utilities: model serialization, FLOP accounting.

Analog of the reference's deeplearning4j-nn util/ package
(ModelSerializer, misc helpers — SURVEY.md §2.1 "Model I/O", "Misc util").
"""

from deeplearning4j_tpu.utils.model_serializer import (
    load_model,
    restore_computation_graph,
    restore_multi_layer_network,
    save_model,
)
from deeplearning4j_tpu.utils.flops import (
    graph_forward_flops,
    mln_forward_flops,
    peak_flops_per_chip,
    train_step_flops,
)
