"""Worker process for tests/test_multihost.py — one simulated host.

Invoked as:
    python multihost_worker.py <coordinator> <num_procs> <proc_id> <out.npz>
with XLA_FLAGS=--xla_force_host_platform_device_count=4, so 2 processes x
4 virtual CPU devices = one 8-device global mesh over "DCN"."""

import sys

import jax

jax.config.update("jax_platforms", "cpu")
# match tests/conftest.py so worker numerics are comparable to the
# in-process baseline
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402


def main():
    coordinator, num_procs, proc_id, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])

    from deeplearning4j_tpu.parallel.multihost import (
        MultiHostDataParallel,
        global_data_parallel_mesh,
        initialize_distributed,
    )

    initialize_distributed(coordinator, num_procs, proc_id)
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    from tests.multihost_common import build_net, global_data

    x, y = global_data()
    # each GLOBAL batch of 16 splits between the processes: this process
    # contributes rows [g*16 + proc*8, g*16 + proc*8 + 8) of batch g
    global_batch, local_batch = 16, 16 // num_procs
    rows = np.concatenate([
        np.arange(g + proc_id * local_batch,
                  g + (proc_id + 1) * local_batch)
        for g in range(0, x.shape[0], global_batch)
    ])
    x_local, y_local = x[rows], y[rows]

    net = build_net()
    mesh = global_data_parallel_mesh()
    trainer = MultiHostDataParallel(net, mesh)
    trainer.fit_local_shards(
        _local_iter(x_local, y_local, batch=local_batch), epochs=2)

    if proc_id == 0:
        flat = {}
        for i, p in enumerate(net.params_list):
            for k, v in p.items():
                flat[f"{i}/{k}"] = np.asarray(v)
        np.savez(out_path, **flat)
    # all processes must exit cleanly together
    jax.effects_barrier()


def _local_iter(x, y, batch):
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator

    dss = [DataSet(x[i:i + batch], y[i:i + batch])
           for i in range(0, x.shape[0], batch)]
    return ExistingDataSetIterator(dss)


if __name__ == "__main__":
    main()
