"""K-means clustering with device-side Lloyd iterations.

Capability parity with the reference's KMeansClustering
(clustering/kmeans/KMeansClustering.java:43-49 — setup(clusterCount,
maxIterationCount, distanceFunction) / setup(clusterCount,
minDistributionVariationRate, ...) over the BaseClusteringAlgorithm
iterate-until-converged framework). TPU-first redesign: one jitted Lloyd
step — an [n, k] distance block (matmul), argmin assignment, and a
segment-sum centroid update — instead of the reference's per-point Java
loops; the host only checks convergence scalars between steps.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.distances import is_similarity, pairwise


@dataclasses.dataclass
class Cluster:
    """One cluster of a ClusterSet: its center and member point indices."""

    center: np.ndarray
    point_indices: np.ndarray

    @property
    def count(self) -> int:
        return int(self.point_indices.size)


@dataclasses.dataclass
class ClusterSet:
    """Result of a clustering run (reference: cluster/ClusterSet.java)."""

    centers: np.ndarray          # [k, d]
    assignments: np.ndarray      # [n] cluster index per point
    distances: np.ndarray        # [n] distance of each point to its center
    distance_function: str
    iterations: int

    @property
    def clusters(self) -> List[Cluster]:
        return [
            Cluster(self.centers[c], np.nonzero(self.assignments == c)[0])
            for c in range(self.centers.shape[0])
        ]

    def nearest_cluster(self, point: np.ndarray) -> int:
        d = np.asarray(pairwise(jnp.asarray(point)[None, :],
                                jnp.asarray(self.centers),
                                self.distance_function))[0]
        return int(np.argmax(d) if is_similarity(self.distance_function)
                   else np.argmin(d))


@partial(jax.jit, static_argnums=(2,))
def _lloyd_step(points, centers, distance):
    """One Lloyd iteration: assign + recompute. Distances as matmul;
    similarity functions (cosine) assign by argmax and renormalize the
    centers (spherical k-means)."""
    d = pairwise(points, centers, distance)
    if is_similarity(distance):
        assign = jnp.argmax(d, axis=1)
        best = jnp.max(d, axis=1)
    else:
        assign = jnp.argmin(d, axis=1)
        best = jnp.min(d, axis=1)
    k = centers.shape[0]
    onehot = jax.nn.one_hot(assign, k, dtype=points.dtype)       # [n, k]
    sums = onehot.T @ points                                     # [k, d]
    counts = jnp.sum(onehot, axis=0)[:, None]                    # [k, 1]
    new_centers = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0),
                            centers)
    if distance == "cosinesimilarity":
        norm = jnp.sqrt(jnp.sum(new_centers * new_centers, axis=1,
                                keepdims=True))
        new_centers = new_centers / jnp.maximum(norm, 1e-12)
    return new_centers, assign, best


class KMeansClustering:
    """setup(cluster_count, max_iterations, distance) -> .apply_to(points).

    Convergence: stops when the assignment-distribution variation rate
    drops below ``min_distribution_variation_rate`` (the reference's
    ConvergenceCondition) or after ``max_iterations``.
    """

    def __init__(self, cluster_count: int, max_iterations: int = 100,
                 distance_function: str = "euclidean",
                 min_distribution_variation_rate: float = 1e-4,
                 seed: int = 0, init: str = "kmeans++"):
        if distance_function not in ("euclidean", "sqeuclidean", "manhattan",
                                     "cosinesimilarity"):
            # 'dot' has no meaningful centroid objective — reject it
            raise ValueError(
                f"k-means supports euclidean/sqeuclidean/manhattan/"
                f"cosinesimilarity, got {distance_function!r}")
        self.cluster_count = int(cluster_count)
        self.max_iterations = int(max_iterations)
        self.distance_function = distance_function
        self.min_rate = float(min_distribution_variation_rate)
        self.seed = seed
        self.init = init

    @classmethod
    def setup(cls, cluster_count: int, max_iterations: int = 100,
              distance_function: str = "euclidean", **kw) -> "KMeansClustering":
        return cls(cluster_count, max_iterations, distance_function, **kw)

    # -- init ---------------------------------------------------------------

    def _init_centers(self, points: jnp.ndarray) -> jnp.ndarray:
        n = points.shape[0]
        rng = np.random.default_rng(self.seed)
        k = self.cluster_count
        if self.init == "random":
            idx = rng.choice(n, size=k, replace=False)
            return points[np.sort(idx)]
        # k-means++ — D^2 sampling; each round's distance update is one
        # device [n] column
        first = int(rng.integers(0, n))
        chosen = [first]
        d2 = np.asarray(_point_d2(points, points[first]))
        for _ in range(1, k):
            mass = float(d2.sum())
            if mass <= 1e-12:  # all remaining points coincide with a center
                nxt = int(rng.integers(0, n))
            else:
                nxt = int(rng.choice(n, p=d2 / mass))
            chosen.append(nxt)
            d2 = np.minimum(d2, np.asarray(_point_d2(points, points[nxt])))
        return points[np.array(chosen)]

    # -- main loop ----------------------------------------------------------

    def apply_to(self, points: np.ndarray) -> ClusterSet:
        pts = jnp.asarray(points, jnp.float32)
        n = pts.shape[0]
        if self.cluster_count > n:
            raise ValueError(f"cluster_count {self.cluster_count} > n {n}")
        centers = self._init_centers(pts)
        prev_assign: Optional[np.ndarray] = None
        assign = best = None
        it = 0
        for it in range(1, self.max_iterations + 1):
            centers, assign_d, best_d = _lloyd_step(
                pts, centers, self.distance_function)
            assign = np.asarray(assign_d)
            best = np.asarray(best_d)
            if prev_assign is not None:
                rate = float(np.mean(assign != prev_assign))
                if rate <= self.min_rate:
                    break
            prev_assign = assign
        dist = best
        return ClusterSet(
            centers=np.asarray(centers),
            assignments=assign,
            distances=dist,
            distance_function=self.distance_function,
            iterations=it,
        )


@jax.jit
def _point_d2(points, center):
    diff = points - center[None, :]
    return jnp.sum(diff * diff, axis=1)
