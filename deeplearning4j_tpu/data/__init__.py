"""Data pipeline: DataSet container, iterators, the staged input pipeline
(multi-worker ETL, device-resident prefetch, on-device batch transforms),
dataset fetchers.

Analog of the reference's DataSet/DataSetIterator framework
(deeplearning4j-nn datasets/ + deeplearning4j-core datasets/iterator/impl/)
plus the AsyncDataSetIterator/DataVec ETL-thread throughput machinery
(MultiLayerNetwork.java:1023-1025), re-shaped for a device with a host
link worth hiding: see data/prefetch.py.
"""

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    ExistingDataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
)
from deeplearning4j_tpu.data.prefetch import (
    DevicePrefetchIterator,
    ParallelDataSetIterator,
)
from deeplearning4j_tpu.data.transforms import DeviceBatchTransform
from deeplearning4j_tpu.data.fetchers import (
    CifarDataSetIterator,
    IrisDataSetIterator,
    LFWDataSetIterator,
)
