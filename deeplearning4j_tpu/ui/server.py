"""Training dashboard HTTP server.

Reference: PlayUIServer (deeplearning4j-play, runnable with --uiPort) +
TrainModule route table (module/train/TrainModule.java:96-112):
/train -> overview, /train/overview(/data), /train/model(/graph,
/data/:layerId), /train/system(/data), /train/sessions/current|all; the
RemoteReceiverModule accepts stats POSTed from remote training processes.

Self-contained stdlib implementation: JSON data routes consumed by an
inline HTML/SVG dashboard (no external assets — the box it runs on may
have zero egress), polling /train/overview/data every 2s.
"""

from __future__ import annotations

import json
import struct
from typing import Optional
from urllib.parse import urlparse

from deeplearning4j_tpu.ui.codec import decode_record
from deeplearning4j_tpu.ui.stats import split_stat_key
from deeplearning4j_tpu.ui.storage import StatsStorage
from deeplearning4j_tpu.utils.jsonhttp import (
    JsonHttpServer,
    html_response,
    json_response,
)


_PAGE = """<!doctype html>
<html><head><title>dl4j-tpu training UI</title>
<style>
 body {{ font-family: sans-serif; margin: 1.5em; background: #fafafa; }}
 h1 {{ font-size: 1.2em; }} h2 {{ font-size: 1em; color: #444; }}
 .chart {{ background: #fff; border: 1px solid #ddd; margin: 0.6em;
           padding: 0.4em; display: inline-block; }}
 nav a {{ margin-right: 1.2em; }}
 table {{ border-collapse: collapse; }} td, th {{ border: 1px solid #ccc;
   padding: 2px 8px; font-size: 0.85em; }}
</style></head>
<body>
<nav><a href="/train/overview">overview</a><a href="/train/model">model</a>
<a href="/train/flow">flow</a>
<a href="/train/system">system</a><a href="/train/histogram">histogram</a>
<a href="/train/activations">activations</a><a href="/train/alerts">alerts</a>
<a href="/tsne">tsne</a></nav>
<h1>dl4j-tpu training — {title}</h1>
<div id="content">loading…</div>
<script>
const VIEW = "{view}";
function line(points, w, h, color) {{
  if (points.length < 2) return "<svg width="+w+" height="+h+"></svg>";
  const xs = points.map(p => p[0]), ys = points.map(p => p[1]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs);
  const y0 = Math.min(...ys), y1 = Math.max(...ys);
  const sx = x => 4 + (w-8) * (x - x0) / Math.max(x1 - x0, 1e-9);
  const sy = y => h - 4 - (h-8) * (y - y0) / Math.max(y1 - y0, 1e-9);
  const d = points.map((p,i) => (i?"L":"M") + sx(p[0]).toFixed(1) + "," +
                                sy(p[1]).toFixed(1)).join(" ");
  return `<svg width=${{w}} height=${{h}}><path d="${{d}}" fill="none"
          stroke="${{color}}" stroke-width="1.5"/></svg>
          <div style="font-size:0.7em;color:#888">min ${{y0.toPrecision(4)}}
          max ${{y1.toPrecision(4)}}</div>`;
}}
function chart(title, pts, color) {{
  return `<div class="chart"><h2>${{title}}</h2>${{line(pts,380,160,color)}}</div>`;
}}
async function refresh() {{
  const r = await fetch("/train/" + VIEW + "/data");
  const d = await r.json();
  let html = "";
  if (VIEW == "overview") {{
    html += chart("score vs iteration", d.score, "#1565c0");
    html += chart("samples/sec", d.samples_per_sec, "#2e7d32");
    html += chart("update:param ratio (log10)", d.update_ratio, "#c62828");
    html += chart("etl ms", d.etl_ms, "#6a1b9a");
  }} else if (VIEW == "model") {{
    for (const layer of d.layers) {{
      html += `<h2>layer ${{layer.index}} — ${{layer.type}}
               (${{layer.n_params}} params)</h2>`;
      for (const [name, pts] of Object.entries(layer.series))
        html += chart(name, pts, "#00695c");
    }}
  }} else if (VIEW == "histogram") {{
    html += `<p>iteration ${{d.iteration}}</p>`;
    for (const [name, h] of Object.entries(d.hists || {{}})) {{
      const n = h.counts.length, W = 380, H = 140;
      const mx = Math.max(...h.counts, 1);
      let bars = "";
      for (let i = 0; i < n; i++) {{
        const bh = (H - 20) * h.counts[i] / mx;
        bars += `<rect x=${{(i * W / n).toFixed(1)}} y=${{(H - bh).toFixed(1)}}
                 width=${{(W / n - 1).toFixed(1)}} height=${{bh.toFixed(1)}}
                 fill="#1565c0"/>`;
      }}
      html += `<div class="chart"><h2>${{name}}</h2>
        <svg width=${{W}} height=${{H}}>${{bars}}</svg>
        <div style="font-size:0.7em;color:#888">
        [${{h.edges[0].toPrecision(3)}}, ${{h.edges[n].toPrecision(3)}}]
        </div></div>`;
    }}
  }} else if (VIEW == "activations") {{
    const a = d.activations;
    if (!a) {{ html = "no activation frames yet"; }}
    else {{
      html += `<p>layer ${{a.layer}}, iteration ${{d.iteration}}</p>`;
      a.channels.forEach((ch, ci) => {{
        const h = ch.length, w = ch[0].length, S = 4;
        html += `<canvas id="act${{ci}}" width=${{w * S}} height=${{h * S}}
                 style="border:1px solid #ddd;margin:4px"></canvas>`;
      }});
      setTimeout(() => a.channels.forEach((ch, ci) => {{
        const h = ch.length, w = ch[0].length, S = 4;
        const ctx = document.getElementById("act" + ci).getContext("2d");
        for (let y = 0; y < h; y++) for (let x = 0; x < w; x++) {{
          const v = Math.round(255 * ch[y][x]);
          ctx.fillStyle = `rgb(${{v}},${{v}},${{v}})`;
          ctx.fillRect(x * S, y * S, S, S);
        }}
      }}), 0);
    }}
  }} else if (VIEW == "flow") {{
    html += `<div class="chart">${{d.svg || "(no graph yet)"}}</div>`;
  }} else if (VIEW == "alerts") {{
    if (!d.ledger) {{
      html += `<p>${{d.note || "no run ledger attached"}}</p>`;
    }} else {{
      html += `<p>run <code>${{d.run_id}}</code> — ledger
               <code>${{d.ledger}}</code></p>`;
      html += "<table><tr><th>rule</th><th>state</th><th>severity</th>"
            + "<th>value</th><th>definition</th></tr>";
      for (const r of d.rules || []) {{
        const color = r.state == "firing" ? "#c62828"
                    : r.state == "pending" ? "#ef6c00" : "#2e7d32";
        html += `<tr><td>${{r.rule}}</td>
                 <td style="color:${{color}}"><b>${{r.state}}</b></td>
                 <td>${{r.severity}}</td><td>${{r.value ?? ""}}</td>
                 <td>${{r.detail}}</td></tr>`;
      }}
      html += "</table>";
      if ((d.transitions || []).length) {{
        html += "<h2>recent transitions</h2><table>"
              + "<tr><th>ts</th><th>rule</th><th>to</th><th>value</th></tr>";
        for (const t of d.transitions.slice(-20).reverse())
          html += `<tr><td>${{t.ts}}</td><td>${{t.rule}}</td>
                   <td>${{t.to}}</td><td>${{t.value ?? ""}}</td></tr>`;
        html += "</table>";
      }}
    }}
  }} else if (VIEW == "tsne") {{
    const W = 760, H = 560;
    let pts = "";
    if (d.coords && d.coords.length) {{
      const xs = d.coords.map(c => c[0]), ys = d.coords.map(c => c[1]);
      const x0 = Math.min(...xs), x1 = Math.max(...xs);
      const y0 = Math.min(...ys), y1 = Math.max(...ys);
      d.coords.forEach((c, i) => {{
        const px = 20 + (W - 40) * (c[0] - x0) / Math.max(x1 - x0, 1e-9);
        const py = 20 + (H - 40) * (c[1] - y0) / Math.max(y1 - y0, 1e-9);
        pts += `<circle cx=${{px.toFixed(1)}} cy=${{py.toFixed(1)}} r=3
                fill="#1565c0"/>`;
        if (d.words && d.words[i])
          pts += `<text x=${{(px + 5).toFixed(1)}} y=${{py.toFixed(1)}}
                  font-size="10">${{d.words[i]}}</text>`;
      }});
    }}
    html += `<div class="chart"><h2>t-SNE (${{(d.coords || []).length}}
             points)</h2><svg width=${{W}} height=${{H}}>${{pts}}</svg></div>`;
  }} else {{
    html += "<table><tr><th>key</th><th>value</th></tr>";
    for (const [k,v] of Object.entries(d.static || {{}}))
      html += `<tr><td>${{k}}</td><td>${{JSON.stringify(v)}}</td></tr>`;
    html += "</table>";
    for (const [dev, pts] of Object.entries(d.memory || {{}}))
      html += chart(dev + " bytes in use", pts, "#ef6c00");
    for (const [name, pts] of Object.entries(d.live || {{}}))
      html += chart(name, pts, "#00838f");
  }}
  document.getElementById("content").innerHTML = html;
}}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class UIServer:
    """UIServer(storage, port=9090).start() -> bound port."""

    _instance = None

    def __init__(self, storage: StatsStorage, port: int = 9090):
        self.storage = storage
        self._tsne = {"words": [], "coords": []}
        # live registry gauge history for the system view: sampled once
        # per /train/system/data poll (the dashboard's own 2s cadence —
        # no extra thread), bounded per series. This is what makes the
        # PR 9 headline gauges (step_mfu, step_flops_per_second,
        # device_memory_bytes{kind}) and the serving queue depth visible
        # in the UI instead of only in a Prometheus scrape.
        self._sys_hist: dict = {}
        self._sys_t0 = None
        # JsonHttpServer handles requests on multiple threads: the
        # history dict is mutated per poll and must not be iterated
        # concurrently with an insert
        import threading

        self._sys_lock = threading.Lock()
        self._server = JsonHttpServer(get=self._get, post=self._post,
                                      port=port)

    @property
    def port(self) -> int:
        return self._server.port

    @classmethod
    def get_instance(cls, storage: Optional[StatsStorage] = None,
                     port: int = 9090) -> "UIServer":
        """Singleton accessor (reference: UIServer.getInstance())."""
        if cls._instance is None:
            from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

            cls._instance = cls(storage or InMemoryStatsStorage(), port)
            cls._instance.start()
        return cls._instance

    # -- data assembly -------------------------------------------------------

    def _current_session(self) -> Optional[str]:
        return self.storage.latest_session_id()

    def _score_updates(self, session: Optional[str]) -> list:
        """Training-progress records only — the stream also carries
        activation frames (ConvolutionalIterationListener) without a
        score."""
        ups = self.storage.get_updates(session) if session else []
        return [u for u in ups if "score" in u]

    def _overview_data(self, session: Optional[str]) -> dict:
        ups = self._score_updates(session)
        import math

        def ratio(u):
            um, pm = u.get("update_mm"), u.get("param_mm")
            if not um or not pm:
                return None
            us = sum(um.values()) / max(len(um), 1)
            ps = sum(pm.values()) / max(len(pm), 1)
            if us <= 0 or ps <= 0:
                return None
            return math.log10(us / ps)

        return {
            "session": session,
            "score": [[u["iteration"], u["score"]] for u in ups],
            "samples_per_sec": [
                [u["iteration"], u["samples_per_sec"]] for u in ups],
            "etl_ms": [[u["iteration"], u["etl_ms"]] for u in ups],
            "update_ratio": [
                [u["iteration"], r] for u in ups
                if (r := ratio(u)) is not None],
        }

    def _histogram_data(self, session: Optional[str]) -> dict:
        """Newest parameter-histogram record (HistogramModule analog)."""
        for u in reversed(self._score_updates(session)):
            if "hists" in u:
                return {"session": session, "iteration": u["iteration"],
                        "hists": u["hists"]}
        return {"session": session, "iteration": None, "hists": {}}

    def _activations_data(self, session: Optional[str]) -> dict:
        """Newest conv-activation frame (ConvolutionalListenerModule)."""
        ups = self.storage.get_updates(session) if session else []
        for u in reversed(ups):
            if "activations" in u:
                return {"session": session, "iteration": u["iteration"],
                        "activations": u["activations"]}
        return {"session": session, "iteration": None, "activations": None}

    def _model_data(self, session: Optional[str]) -> dict:
        ups = self._score_updates(session)
        static = (self.storage.get_static_info(session) or {}) if session else {}
        layers = []
        for meta in static.get("layers", []):
            li = meta["index"]
            series = {}
            for group, label in (("grad_mm", "grad"), ("update_mm", "update"),
                                 ("param_mm", "param")):
                for u in ups:
                    g = u.get(group) or {}
                    for k, v in g.items():
                        kli, pname = split_stat_key(k)
                        if kli == str(li):
                            series.setdefault(
                                f"{label} |{pname}|", []
                            ).append([u["iteration"], v])
            layers.append({**meta, "series": series})
        return {"session": session, "layers": layers}

    # registry families charted on the system page (exact family names;
    # every labeled child becomes its own series)
    _SYSTEM_GAUGES = ("step_mfu", "step_flops_per_second",
                      "step_device_seconds", "device_memory_bytes",
                      "serving_queue_depth")

    def _sample_system_gauges(self) -> dict:
        """Append the live devprof/serving gauges to the bounded
        per-series history and return {series: [[t, v], ...]} — called
        from the data route, so history advances at the dashboard's own
        poll cadence and costs nothing when nobody is watching."""
        import time

        from deeplearning4j_tpu.utils.metrics import get_registry

        try:
            scalars = get_registry().scalar_values()
        except Exception:
            scalars = {}
        with self._sys_lock:
            if self._sys_t0 is None:
                self._sys_t0 = time.time()
            t = round(time.time() - self._sys_t0, 1)
            for key, v in scalars.items():
                if key.split("{")[0] in self._SYSTEM_GAUGES:
                    hist = self._sys_hist.setdefault(key, [])
                    hist.append([t, v])
                    del hist[:-300]  # bounded: ~10 min at the 2s poll
            return {k: list(v) for k, v in self._sys_hist.items()}

    def _system_data(self, session: Optional[str]) -> dict:
        ups = self.storage.get_updates(session) if session else []
        static = (self.storage.get_static_info(session) or {}) if session else {}
        memory = {}
        for u in ups:
            for dev, m in (u.get("memory") or {}).items():
                memory.setdefault(dev, []).append(
                    [u["iteration"], m.get("bytes_in_use", 0)])
        return {"session": session, "static": static, "memory": memory,
                "live": self._sample_system_gauges()}

    def _alerts_data(self) -> dict:
        """Live SLO rule states from the attached run ledger (the same
        payload as the inference server's GET /alerts)."""
        from deeplearning4j_tpu.utils import runledger

        led = runledger.current()
        if led is None:
            return {"ledger": None, "rules": [], "firing": [],
                    "transitions": [],
                    "note": "no run ledger attached — pass "
                            "run_ledger= to fit()/the server, or "
                            "attach one via utils.runledger"}
        return led.alert_status()

    # -- http ----------------------------------------------------------------

    def _get(self, path, body, headers):
        path = urlparse(path).path.rstrip("/") or "/train/overview"
        session = self._current_session()
        pages = {"/train": "overview", "/train/overview": "overview",
                 "/train/model": "model", "/train/system": "system",
                 "/train/histogram": "histogram",
                 "/train/activations": "activations",
                 "/train/flow": "flow",
                 "/train/alerts": "alerts",
                 "/tsne": "tsne", "/train/tsne": "tsne"}
        if path in pages:
            view = pages[path]
            return html_response(_PAGE.format(title=view, view=view))
        if path == "/train/overview/data":
            return json_response(self._overview_data(session))
        if path == "/train/histogram/data":
            return json_response(self._histogram_data(session))
        if path == "/train/activations/data":
            return json_response(self._activations_data(session))
        if path in ("/train/tsne/data", "/tsne/data"):
            return json_response(self._tsne)
        if path == "/train/model/data":
            return json_response(self._model_data(session))
        if path == "/train/flow/data":
            # flow view (reference: FlowListenerModule): the model DAG
            # rendered server-side by the report DSL's FlowGraph with
            # per-layer latest stats overlaid
            from deeplearning4j_tpu.ui.report import (
                FlowGraph,
                _layer_stats_latest,
            )

            static = (self.storage.get_static_info(session) or {}
                      ) if session else {}
            ups = self._score_updates(session)
            graph = static.get("graph") or {}
            svg = (FlowGraph(graph, _layer_stats_latest(ups, static))
                   .render_html() if graph else None)
            return json_response({"session": session, "graph": graph,
                                  "svg": svg})
        if path == "/train/model/graph":
            st = (self.storage.get_static_info(session) or {}
                  ) if session else {}
            return json_response({"layers": st.get("layers", [])})
        if path == "/train/system/data":
            return json_response(self._system_data(session))
        if path == "/train/alerts/data":
            return json_response(self._alerts_data())
        if path == "/train/sessions/current":
            return json_response({"session": session})
        if path == "/train/sessions/all":
            return json_response(
                {"sessions": self.storage.list_session_ids()})
        return None

    def _post(self, path, body, headers):
        # remote receiver (reference: RemoteReceiverModule) + t-SNE upload
        # (reference: TsneModule POST /tsne/upload)
        session = headers.get("X-Session-Id", "remote")
        path = urlparse(path).path
        try:
            if path == "/remote/static":
                self.storage.put_static_info(session, json.loads(body))
            elif path == "/remote/update":
                self.storage.put_update(session, decode_record(body))
            elif path in ("/tsne/coords", "/tsne/upload"):
                import html

                req = json.loads(body)
                coords = [[float(a), float(b)] for a, b in req["coords"]]
                # words are interpolated into the page's innerHTML — escape
                # server-side so an unauthenticated poster can't plant XSS
                self._tsne = {"words": [html.escape(str(w))
                                        for w in req.get("words", [])],
                              "coords": coords}
            elif path == "/tsne/compute":
                # run the device t-SNE over posted vectors (the tab the
                # reference feeds from files; clustering/tsne.py does the
                # math here)
                import numpy as np

                from deeplearning4j_tpu.clustering import Tsne

                req = json.loads(body)
                x = np.asarray(req["vectors"], np.float32)
                t = Tsne(n_components=2,
                         perplexity=float(req.get("perplexity", 20.0)),
                         n_iter=int(req.get("iters", 300)))
                import html

                coords = t.fit_transform(x)
                self._tsne = {"words": [html.escape(str(w))
                                        for w in req.get("words", [])],
                              "coords": np.asarray(coords).tolist()}
            else:
                return None
            return json_response({"status": "ok"})
        except (ValueError, KeyError, IndexError, struct.error) as e:
            return json_response({"error": str(e)}, 400)

    def start(self) -> int:
        return self._server.start()

    def stop(self):
        self._server.stop()
        if UIServer._instance is self:
            UIServer._instance = None
