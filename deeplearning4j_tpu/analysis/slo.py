"""Declarative SLO rules over metric samples — the judgment layer on
top of the run ledger (utils/runledger).

PRs 3/6/9/11 made every subsystem *measured*: MFU/HBM gauges, shed
books, deadline outcomes, exemplars. But a gauge is not a verdict — an
operator (or the future autotune controller) needs "the p99 objective
is burning error budget 4x too fast" as a machine-readable, debounced
state, not a number to eyeball. This module is that rules layer,
deliberately shaped like the Prometheus alerting model (rule + `for:`
debounce + pending/firing lifecycle) evaluated in-process on the
ledger's recorder thread — no external alerting stack on the box.

Rule kinds (one `SLORule` each, JSON-serializable):

* `threshold`       — series `op` value (e.g. `serving_queue_depth >
                      capacity`: queue boundedness violated).
* `rate_of_change`  — per-second delta of a series `op` value (counter
                      velocity: a shed storm, a compile storm).
* `burn_rate`       — windowed error-budget burn against an objective
                      like "99% of requests complete under
                      `default_deadline_ms`": from a histogram's
                      cumulative bucket counts, bad_fraction /
                      (1 - objective) over the window must stay under
                      `max_burn`. The classic multi-window SRE signal,
                      single-window here (the ledger's cadence IS the
                      short window).
* `drift`           — series compared against a REFERENCE value from
                      the PR 9 cost model: live `step_mfu` below a
                      configured fraction of the roofline ceiling,
                      `device_memory_bytes{kind="live"}` above a
                      fraction of the JX008 residency budget. Same
                      check as threshold, but the rule records where
                      its limit came from.

Lifecycle per rule: ok -> pending (first violating sample) -> firing
(still violating after `for_seconds`) -> resolved (first clean sample)
-> ok. Transitions are returned to the caller; the LIVE side effects
(slo_alerts_total, health DEGRADED, flight-recorder events, findings)
belong to utils/runledger so offline re-evaluation (`cli slo --ledger`)
is pure — replaying a recorded run must never mutate this process's
health.

Series selectors: a rule's `series` names a metric family
(`step_mfu` matches `step_mfu{source="costmodel"}`), optionally with a
label subset (`device_memory_bytes{kind="live"}`). A rule whose
selector matches nothing is simply not violated — absence of data is
not an alert (the ledger records what the process measured; a process
that never served has no latency objective to burn).

`default_rule_pack()` derives the standing rules from what is attached:
the serving config's deadline/queue knobs and the cost model's
roofline/residency ceilings — the "judged continuously" bridge ROADMAP
item 4's controller consumes.

Finding code (documented in analysis/findings.py):
  SLO001  a rule entered `firing` (severity = the rule's own)
"""

from __future__ import annotations

import dataclasses
import json
import math
import re
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

ERROR = "error"
WARNING = "warning"
INFO = "info"

OK = "ok"
PENDING = "pending"
FIRING = "firing"

_OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}

_SELECTOR_RE = re.compile(r"^([^{]+?)(\{(.*)\})?$")


def _parse_selector(sel: str) -> Tuple[str, Dict[str, str]]:
    """`name` or `name{k="v",...}` -> (family, label filter); the name
    may carry a `:count`/`:sum` facet for histogram-backed threshold
    rules. Quotes on label values are optional; a malformed selector
    raises at rule construction, not silently at evaluation."""
    m = _SELECTOR_RE.match(sel.strip())
    if not m:
        raise ValueError(f"bad series selector {sel!r}")
    name = m.group(1).strip()
    labels: Dict[str, str] = {}
    body = m.group(3)
    if body:
        for part in body.split(","):
            if not part.strip():
                continue
            k, _, v = part.partition("=")
            if not _:
                raise ValueError(f"bad label filter in selector {sel!r}")
            labels[k.strip()] = v.strip().strip('"')
    return name, labels


def _split_key(key: str) -> Tuple[str, Dict[str, str], str]:
    """A scalar_values() key -> (family, labels, suffix) where suffix is
    "", "count", "sum", or "bucket:<le>"."""
    suffix = ""
    base = key
    i = key.find("}")
    sep = key.find(":", i + 1 if i >= 0 else 0)
    if sep >= 0:
        base, suffix = key[:sep], key[sep + 1:]
    j = base.find("{")
    if j < 0:
        return base, {}, suffix
    family = base[:j]
    labels: Dict[str, str] = {}
    for part in base[j + 1:-1].split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        labels[k] = v.strip('"')
    return family, labels, suffix


def _match(values: Dict[str, float], family: str,
           label_filter: Dict[str, str],
           suffix: str = "") -> List[Tuple[str, float]]:
    """All (key, value) entries whose family matches and whose labels
    are a superset of the filter; `suffix` narrows to plain series (""),
    ":count"/"sum", or "bucket" (any le)."""
    out = []
    for key, v in values.items():
        fam, labels, sfx = _split_key(key)
        if fam != family:
            continue
        if suffix == "bucket":
            if not sfx.startswith("bucket:"):
                continue
        elif sfx != suffix:
            continue
        if all(labels.get(k) == want for k, want in label_filter.items()):
            out.append((key, v))
    return out


def _bucket_le(key: str) -> float:
    le = key.rsplit(":bucket:", 1)[1]
    return math.inf if le == "+Inf" else float(le)


@dataclasses.dataclass
class SLORule:
    """One declarative rule. `kind` selects the check; unused fields for
    a kind stay None and round-trip through JSON untouched.

    Common: `name` (stable id), `series` (selector), `severity`
    (error|warning|info — error is what `cli slo --check` gates on),
    `component` (the utils/health component a firing rule degrades;
    defaults to `slo:<name>`), `for_seconds` (debounce: the condition
    must hold this long before pending escalates to firing).

    threshold / rate_of_change: `op` + `value` (rate_of_change compares
    the per-second delta between consecutive samples).

    burn_rate: `objective` (e.g. 0.99), `threshold_ms` (the latency
    objective — "under the deadline"), `window_seconds` (0 = consecutive
    samples), `max_burn` (budget-burn multiple that fires; 1.0 = exactly
    on budget), `min_events` (don't judge fewer completions than this).

    drift: `op` + `reference` × `frac` is the limit; `reference_source`
    records provenance ("costmodel:mfu_ceiling", "flops:hbm_bytes")."""

    name: str
    kind: str
    series: str
    severity: str = ERROR
    component: str = ""
    for_seconds: float = 0.0
    # threshold / rate_of_change / drift
    op: str = ">"
    value: Optional[float] = None
    # burn_rate
    objective: Optional[float] = None
    threshold_ms: Optional[float] = None
    window_seconds: float = 0.0
    max_burn: float = 1.0
    min_events: int = 10
    # drift
    reference: Optional[float] = None
    frac: Optional[float] = None
    reference_source: str = ""

    def __post_init__(self):
        if self.kind not in ("threshold", "rate_of_change", "burn_rate",
                             "drift"):
            raise ValueError(f"unknown rule kind {self.kind!r}")
        if self.op not in _OPS:
            raise ValueError(f"unknown op {self.op!r}")
        if self.severity not in (ERROR, WARNING, INFO):
            raise ValueError(f"unknown severity {self.severity!r}")
        if not self.component:
            self.component = f"slo:{self.name}"
        _parse_selector(self.series)  # fail fast on a malformed selector
        if self.kind in ("threshold", "rate_of_change") \
                and self.value is None:
            raise ValueError(f"rule {self.name!r}: {self.kind} needs value")
        if self.kind == "burn_rate" and (self.objective is None
                                         or self.threshold_ms is None):
            raise ValueError(
                f"rule {self.name!r}: burn_rate needs objective and "
                f"threshold_ms")
        if self.kind == "drift" and (self.reference is None
                                     or self.frac is None):
            raise ValueError(
                f"rule {self.name!r}: drift needs reference and frac")

    # -- serde ----------------------------------------------------------------

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None and v != ""}

    @classmethod
    def from_dict(cls, d: dict) -> "SLORule":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown SLORule fields {sorted(unknown)}")
        return cls(**d)

    def limit(self) -> Optional[float]:
        """The effective numeric limit (threshold/drift); None for
        burn_rate (its limit is `max_burn`, a ratio)."""
        if self.kind == "drift":
            return self.reference * self.frac
        if self.kind == "burn_rate":
            return None
        return self.value

    def describe(self) -> str:
        if self.kind == "burn_rate":
            return (f"{self.series}: {self.objective:.2%} under "
                    f"{self.threshold_ms:g}ms, burn <= {self.max_burn:g} "
                    f"over {self.window_seconds:g}s")
        lim = self.limit()
        src = f" (= {self.frac:g} x {self.reference_source})" \
            if self.kind == "drift" and self.reference_source else ""
        return f"{self.series} {self.op} {lim:g}{src}"


class _RuleState:
    __slots__ = ("state", "since", "value", "fired_total", "scratch")

    def __init__(self):
        self.state = OK
        self.since: Optional[float] = None
        self.value: Optional[float] = None  # last evaluated worst value
        self.fired_total = 0
        self.scratch: dict = {}


class SLORuleSet:
    """Rules + their lifecycle state. `evaluate(ts, values)` judges one
    sample (the flat scalar_values(include_buckets=True) dict) and
    returns the transitions it caused — each {rule, from, to, ts,
    value, severity, component, detail}. Pure: no registry/health/
    recorder writes (utils/runledger applies those live; `cli slo`
    replays ledgers through this same code offline)."""

    def __init__(self, rules: Iterable[SLORule]):
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        self._states = {r.name: _RuleState() for r in self.rules}

    # -- serde ----------------------------------------------------------------

    def to_dicts(self) -> List[dict]:
        return [r.to_dict() for r in self.rules]

    @classmethod
    def from_dicts(cls, ds: Iterable[dict]) -> "SLORuleSet":
        return cls(SLORule.from_dict(d) for d in ds)

    @classmethod
    def from_json(cls, text: str) -> "SLORuleSet":
        doc = json.loads(text)
        if isinstance(doc, dict):
            doc = doc.get("rules", [])
        return cls.from_dicts(doc)

    # -- evaluation -----------------------------------------------------------

    def evaluate(self, ts: float, values: Dict[str, float]) -> List[dict]:
        transitions = []
        for rule in self.rules:
            st = self._states[rule.name]
            try:
                violated, value = self._check(rule, st, ts, values)
            except Exception as e:  # a rule bug judges nothing, loudly
                violated, value = False, None
                st.scratch["error"] = f"{type(e).__name__}: {e}"
            st.value = value
            old = st.state
            if violated:
                if old == OK:
                    st.state, st.since = PENDING, ts
                if st.state == PENDING \
                        and ts - st.since >= rule.for_seconds:
                    st.state = FIRING
                    st.fired_total += 1
            else:
                st.state, st.since = OK, None
            if st.state != old and (st.state == FIRING
                                    or old == FIRING):
                transitions.append({
                    "rule": rule.name,
                    "from": old,
                    "to": st.state if st.state == FIRING else "resolved",
                    "ts": round(ts, 3),
                    "value": value,
                    "severity": rule.severity,
                    "component": rule.component,
                    "detail": rule.describe(),
                })
        return transitions

    def _check(self, rule: SLORule, st: _RuleState, ts: float,
               values: Dict[str, float]):
        family, labels = _parse_selector(rule.series)
        if rule.kind == "burn_rate":
            return self._check_burn(rule, st, ts, values, family, labels)
        suffix = ""
        for sfx in ("count", "sum"):
            if family.endswith(":" + sfx):  # explicit histogram facet
                family, suffix = family[:-(len(sfx) + 1)], sfx
        matches = _match(values, family, labels, suffix)
        if not matches:
            return False, None
        if rule.kind == "rate_of_change":
            prev = st.scratch.get("prev")
            st.scratch["prev"] = (ts, dict(matches))
            if prev is None or ts <= prev[0]:
                return False, None
            dt = ts - prev[0]
            rates = [(v - prev[1].get(k, v)) / dt for k, v in matches]
            worst = max(rates) if rule.op in (">", ">=") else min(rates)
            return _OPS[rule.op](worst, rule.value), worst
        limit = rule.limit()
        vals = [v for _, v in matches]
        worst = max(vals) if rule.op in (">", ">=") else min(vals)
        return _OPS[rule.op](worst, limit), worst

    def _check_burn(self, rule: SLORule, st: _RuleState, ts: float,
                    values: Dict[str, float], family: str,
                    labels: Dict[str, str]):
        buckets = _match(values, family, labels, "bucket")
        totals = _match(values, family, labels, "count")
        if not buckets or not totals:
            return False, None
        thresh = rule.threshold_ms / 1e3
        # good = cumulative count at the smallest bucket bound >= the
        # objective threshold (summed across label children) — requests
        # inside that bucket but past the exact threshold count as good,
        # which under-fires by at most one bucket's width (documented;
        # pick histogram buckets aligned with the objective to avoid it)
        by_le: Dict[float, float] = {}
        for k, v in buckets:
            le = _bucket_le(k)
            by_le[le] = by_le.get(le, 0.0) + v
        le_good = min((le for le in by_le if le >= thresh),
                      default=math.inf)
        good = by_le.get(le_good, 0.0)
        total = sum(v for _, v in totals)
        win = st.scratch.setdefault("window", deque())
        win.append((ts, good, total))
        # keep at least the previous point so window=0 means
        # consecutive-sample burn; otherwise drop points older than the
        # window
        while len(win) > 2 and win[1][0] < ts - rule.window_seconds:
            win.popleft()
        t0, g0, n0 = win[0]
        d_total = total - n0
        if d_total < rule.min_events:
            return False, st.value if st.state != OK else None
        bad_frac = max(0.0, d_total - (good - g0)) / d_total
        budget = max(1e-9, 1.0 - rule.objective)
        burn = bad_frac / budget
        return burn > rule.max_burn, round(burn, 4)

    # -- readout --------------------------------------------------------------

    def status(self) -> List[dict]:
        out = []
        for rule in self.rules:
            st = self._states[rule.name]
            out.append({
                "rule": rule.name,
                "kind": rule.kind,
                "series": rule.series,
                "severity": rule.severity,
                "component": rule.component,
                "state": st.state,
                "since": st.since,
                "value": st.value,
                "fired_total": st.fired_total,
                "detail": rule.describe(),
            })
        return out

    def firing(self) -> List[str]:
        return [r.name for r in self.rules
                if self._states[r.name].state == FIRING]

    def ever_fired(self, severity: Optional[str] = None) -> List[str]:
        return [r.name for r in self.rules
                if self._states[r.name].fired_total > 0
                and (severity is None or r.severity == severity)]


# -- the default rule pack -----------------------------------------------------

def tenant_burn_rules(tenants: Dict[str, float],
                      sample_every: float = 5.0,
                      severity: str = WARNING) -> List[SLORule]:
    """Per-tenant chip-budget burn rules over the resource meter's
    `tenant_device_seconds_total{tenant,tier}` series (utils/
    resourcemeter). `tenants` maps tenant name -> its device-seconds-
    per-wall-second allowance; the rule judges each tier's spend rate
    separately and fires on the worst one (a tenant burning device time
    in ANY tier faster than its share — 1.0/s is a whole chip). A
    tenant that never spends matches nothing and never alerts, so the
    pack is safe to attach before traffic arrives."""
    debounce = max(0.0, 2.0 * float(sample_every))
    return [SLORule(
        name=f"tenant_chip_budget_burn:{tenant}",
        kind="rate_of_change",
        series=f'tenant_device_seconds_total{{tenant="{tenant}"}}',
        op=">", value=float(budget),
        severity=severity,
        component=f"tenant:{tenant}",
        for_seconds=debounce,
    ) for tenant, budget in sorted(tenants.items())]


def default_rule_pack(cost_model=None, serving: Optional[dict] = None,
                      sample_every: float = 5.0,
                      grad_norm_rate: float = 10.0,
                      tenants: Optional[Dict[str, float]] = None
                      ) -> List[SLORule]:
    """Standing rules derived from what this process attached:

    * serving (dict with `default_deadline_ms` / `queue_capacity` /
      `component`): the p99 deadline burn-rate objective over completed
      request latency, and queue boundedness.
    * cost_model (analysis/costmodel.CostModel): live `step_mfu` below
      half the roofline MFU ceiling (warning — the measured/modelled
      gap is tuning signal, not an outage) and
      `device_memory_bytes{kind="live"}` above 90% of the JX008
      residency budget (error; only on backends that report HBM).
    * tenants (dict tenant -> device-seconds/s allowance): one
      per-tenant chip-budget burn rule each (tenant_burn_rules) —
      a tenant outspending its share of the chips turns from a number
      in GET /tenants into a debounced firing state.
    * always: any OOM reaching the forensics path is an error, and the
      sentinel's `train_grad_norm` gauge growing faster than
      `grad_norm_rate`/s is a WARNING — the divergence *precursor*: the
      run ledger records the gradient starting to climb before a loss
      ever goes non-finite, so a post-mortem (`cli slo --ledger`) shows
      when the run began to destabilize, not just when it died. The
      absolute rate is model-scale dependent; tune it per workload. The
      selector matching nothing (no sentinel attached) never alerts.

    `for_seconds` debounces to ~2 ledger samples so a single noisy
    window cannot flip a verdict."""
    debounce = max(0.0, 2.0 * float(sample_every))
    rules = [SLORule(
        name="oom",
        kind="rate_of_change",
        series="oom_total",
        op=">", value=0.0,
        severity=ERROR,
        component="device",
        for_seconds=0.0,
    ), SLORule(
        name="grad_norm_divergence_precursor",
        kind="rate_of_change",
        series="train_grad_norm",
        op=">", value=float(grad_norm_rate),
        severity=WARNING,
        component="fit",
        for_seconds=debounce,
    )]
    if serving:
        component = serving.get("component", "serving")
        deadline = serving.get("default_deadline_ms")
        if deadline:
            rules.append(SLORule(
                name="serving_p99_deadline_burn",
                kind="burn_rate",
                series="serving_output_seconds",
                objective=0.99,
                threshold_ms=float(deadline),
                window_seconds=max(60.0, 12.0 * sample_every),
                max_burn=2.0,
                min_events=20,
                severity=ERROR,
                component=component,
                for_seconds=debounce,
            ))
        cap = serving.get("queue_capacity")
        if cap:
            # the boundedness invariant, not a load signal: admission
            # keeps the request queue <= queue_capacity, and the
            # serving_queue_depth gauge ALSO counts the prepared groups
            # in the collector->dispatcher handoff — so the limit adds
            # that slack. Under healthy 2x overload this rule stays
            # silent (load shows up as sheds); it fires only when the
            # bound itself is broken.
            handoff = serving.get("handoff_capacity", 2)
            rules.append(SLORule(
                name="serving_queue_unbounded",
                kind="threshold",
                series="serving_queue_depth",
                op=">", value=float(cap) + float(handoff),
                severity=ERROR,
                component=component,
                for_seconds=0.0,
            ))
    if cost_model is not None:
        roof = cost_model.roofline()
        ceiling = roof.get("mfu_ceiling")
        if ceiling:
            rules.append(SLORule(
                name="mfu_below_roofline",
                kind="drift",
                series="step_mfu",
                op="<",
                reference=float(ceiling), frac=0.5,
                reference_source="costmodel:mfu_ceiling",
                severity=WARNING,
                component="fit",
                for_seconds=debounce,
            ))
        from deeplearning4j_tpu.utils import flops as _flops

        hbm = _flops.peak_hbm_bytes_per_chip()
        if hbm:
            rules.append(SLORule(
                name="hbm_residency",
                kind="drift",
                series='device_memory_bytes{kind="live"}',
                op=">",
                reference=float(hbm), frac=0.9,
                reference_source="flops:peak_hbm_bytes_per_chip "
                                 "(the JX008 budget)",
                severity=ERROR,
                component="device",
                for_seconds=debounce,
            ))
    if tenants:
        rules.extend(tenant_burn_rules(tenants, sample_every=sample_every))
    return rules


# -- offline re-evaluation (cli slo) ------------------------------------------

def evaluate_ledger(samples: Iterable[Tuple[float, Dict[str, float]]],
                    rules: Iterable[SLORule]) -> dict:
    """Replay a recorded run's absolute samples through a FRESH rule-set
    — the CI/soak gate behind `cli slo --ledger ... --check`. Pure (no
    health/metrics side effects). Returns {rules, transitions,
    ever_fired, ever_fired_errors, firing_at_end, ok}; `ok` is False
    when any ERROR-severity rule fired at any point during the run."""
    rs = SLORuleSet(rules)
    transitions: List[dict] = []
    n = 0
    for ts, values in samples:
        n += 1
        transitions.extend(rs.evaluate(ts, values))
    fired_err = rs.ever_fired(ERROR)
    return {
        "samples": n,
        "rules": rs.status(),
        "transitions": transitions,
        "ever_fired": rs.ever_fired(),
        "ever_fired_errors": fired_err,
        "firing_at_end": rs.firing(),
        "ok": not fired_err,
    }
