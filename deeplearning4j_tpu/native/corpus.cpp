// Native corpus pipeline — tokenization + vocab construction + indexing.
//
// The runtime-side analog of the reference's text pipeline
// (text/tokenization/ + VocabConstructor.java, 612 LoC, which fans out
// Java worker threads because per-token JVM work was the bottleneck).
// Here the whole pass — read, tokenize, hash-count, frequency-sort,
// re-index — runs in C++ behind a ctypes boundary; Python sees only
// numpy arrays. A pure-Python dict/Counter pass over a multi-GB corpus
// is 10-30x slower and holds the GIL the whole time.
//
// Contract (must match nlp/vocab.VocabConstructor): vocabulary sorted by
// (count desc, word asc); tokens split on ASCII whitespace; optional
// lowercasing.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 corpus.cpp -o libdl4jcorpus.so
// (native/__init__.py does this on first use and caches the .so).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Corpus {
    // token stream as indices into `words` (pre-filter ids)
    std::vector<int64_t> stream;
    std::vector<int64_t> sentence_offsets;  // start of each sentence
    std::vector<std::string> words;         // first-seen order
    std::vector<int64_t> counts;            // aligned with words

    // filtered+sorted view (built per min_count)
    int64_t cached_min_count = -1;
    std::vector<int64_t> rank;      // pre-filter id -> vocab index or -1
    std::vector<int64_t> vocab_ids; // vocab index -> pre-filter id

    // GloVe co-occurrence view (built per min_count/window/symmetric)
    int64_t cooc_min_count = -1, cooc_window = -1, cooc_symmetric = -1;
    std::vector<int32_t> cooc_rows, cooc_cols;
    std::vector<float> cooc_vals;
};

inline bool is_space(char c) {
    return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v'
        || c == '\f';
}

void build_ranks(Corpus* c, int64_t min_count) {
    if (c->cached_min_count == min_count) return;
    std::vector<int64_t> keep;
    keep.reserve(c->words.size());
    for (int64_t i = 0; i < (int64_t)c->words.size(); ++i)
        if (c->counts[i] >= min_count) keep.push_back(i);
    // (count desc, word asc) — the VocabConstructor ordering
    std::sort(keep.begin(), keep.end(), [&](int64_t a, int64_t b) {
        if (c->counts[a] != c->counts[b]) return c->counts[a] > c->counts[b];
        return c->words[a] < c->words[b];
    });
    c->rank.assign(c->words.size(), -1);
    for (int64_t r = 0; r < (int64_t)keep.size(); ++r)
        c->rank[keep[r]] = r;
    c->vocab_ids = std::move(keep);
    c->cached_min_count = min_count;
}

}  // namespace

extern "C" {

// Tokenize + count a whole file. Returns an opaque handle (nullptr on
// I/O failure). newline = sentence boundary.
void* corpus_open(const char* path, int lowercase) {
    std::ifstream f(path, std::ios::binary);
    if (!f) return nullptr;
    auto* c = new Corpus();
    std::unordered_map<std::string, int64_t> ids;
    std::string line, tok;
    while (std::getline(f, line)) {
        c->sentence_offsets.push_back((int64_t)c->stream.size());
        size_t i = 0, n = line.size();
        while (i < n) {
            while (i < n && is_space(line[i])) ++i;
            size_t j = i;
            while (j < n && !is_space(line[j])) ++j;
            if (j > i) {
                tok.assign(line, i, j - i);
                if (lowercase)
                    for (auto& ch : tok)
                        if (ch >= 'A' && ch <= 'Z') ch += 32;
                auto it = ids.find(tok);
                int64_t id;
                if (it == ids.end()) {
                    id = (int64_t)c->words.size();
                    ids.emplace(tok, id);
                    c->words.push_back(tok);
                    c->counts.push_back(0);
                } else {
                    id = it->second;
                }
                ++c->counts[id];
                c->stream.push_back(id);
            }
            i = j;
        }
    }
    c->sentence_offsets.push_back((int64_t)c->stream.size());
    return c;
}

void corpus_close(void* h) { delete static_cast<Corpus*>(h); }

int64_t corpus_total_tokens(void* h) {
    return (int64_t)static_cast<Corpus*>(h)->stream.size();
}

int64_t corpus_num_sentences(void* h) {
    return (int64_t)static_cast<Corpus*>(h)->sentence_offsets.size() - 1;
}

int64_t corpus_vocab_size(void* h, int64_t min_count) {
    auto* c = static_cast<Corpus*>(h);
    build_ranks(c, min_count);
    return (int64_t)c->vocab_ids.size();
}

// Byte length of the '\n'-joined vocab dump (for buffer sizing).
int64_t corpus_vocab_bytes(void* h, int64_t min_count) {
    auto* c = static_cast<Corpus*>(h);
    build_ranks(c, min_count);
    int64_t total = 0;
    for (int64_t id : c->vocab_ids) total += (int64_t)c->words[id].size() + 1;
    return total;
}

// Write words ('\n'-joined, vocab order) into buf and counts into
// counts_out [vocab_size]. Returns bytes written, or -1 if buf too small.
int64_t corpus_vocab_dump(void* h, int64_t min_count, char* buf,
                          int64_t buf_len, int64_t* counts_out) {
    auto* c = static_cast<Corpus*>(h);
    build_ranks(c, min_count);
    int64_t off = 0;
    for (int64_t r = 0; r < (int64_t)c->vocab_ids.size(); ++r) {
        const std::string& w = c->words[c->vocab_ids[r]];
        if (off + (int64_t)w.size() + 1 > buf_len) return -1;
        std::memcpy(buf + off, w.data(), w.size());
        off += (int64_t)w.size();
        buf[off++] = '\n';
        counts_out[r] = c->counts[c->vocab_ids[r]];
    }
    return off;
}

// Re-index the token stream against the (min_count-filtered) vocab:
// tokens_out [total_tokens] gets the vocab index or -1 (filtered word);
// offsets_out [num_sentences + 1] gets sentence start offsets.
void corpus_index(void* h, int64_t min_count, int32_t* tokens_out,
                  int64_t* offsets_out) {
    auto* c = static_cast<Corpus*>(h);
    build_ranks(c, min_count);
    for (size_t i = 0; i < c->stream.size(); ++i)
        tokens_out[i] = (int32_t)c->rank[c->stream[i]];
    for (size_t i = 0; i < c->sentence_offsets.size(); ++i)
        offsets_out[i] = c->sentence_offsets[i];
}

// -- GloVe co-occurrence accumulation ---------------------------------------
// Forward-window scan with 1/distance weighting over the min_count-filtered
// sentence stream (the AbstractCoOccurrences.java:322-374 semantics: for
// each position x, partners j in (x, x+window]; weight 1/(j-x); symmetric
// mirrors each increment). One C++ pass replaces the reference's
// multi-threaded CountMap shuffling; Python receives COO arrays.

int64_t corpus_cooc_build(void* h, int64_t min_count, int64_t window,
                          int symmetric) {
    auto* c = static_cast<Corpus*>(h);
    if (c->cooc_min_count == min_count && c->cooc_window == window &&
        c->cooc_symmetric == symmetric)
        return (int64_t)c->cooc_vals.size();
    build_ranks(c, min_count);
    const int64_t V = (int64_t)c->vocab_ids.size();
    std::unordered_map<int64_t, double> acc;
    std::vector<int64_t> sent;
    for (size_t s = 0; s + 1 < c->sentence_offsets.size(); ++s) {
        sent.clear();
        for (int64_t t = c->sentence_offsets[s];
             t < c->sentence_offsets[s + 1]; ++t) {
            int64_t r = c->rank[c->stream[t]];
            if (r >= 0) sent.push_back(r);  // filtered words drop out
        }
        const int64_t n = (int64_t)sent.size();
        for (int64_t x = 0; x < n; ++x) {
            int64_t stop = std::min(x + window + 1, n);
            for (int64_t j = x + 1; j < stop; ++j) {
                double w = 1.0 / (double)(j - x);
                acc[sent[x] * V + sent[j]] += w;
                if (symmetric) acc[sent[j] * V + sent[x]] += w;
            }
        }
    }
    c->cooc_rows.clear(); c->cooc_cols.clear(); c->cooc_vals.clear();
    c->cooc_rows.reserve(acc.size());
    c->cooc_cols.reserve(acc.size());
    c->cooc_vals.reserve(acc.size());
    for (const auto& kv : acc) {
        c->cooc_rows.push_back((int32_t)(kv.first / V));
        c->cooc_cols.push_back((int32_t)(kv.first % V));
        c->cooc_vals.push_back((float)kv.second);
    }
    c->cooc_min_count = min_count;
    c->cooc_window = window;
    c->cooc_symmetric = symmetric;
    return (int64_t)c->cooc_vals.size();
}

void corpus_cooc_dump(void* h, int32_t* rows_out, int32_t* cols_out,
                      float* vals_out) {
    auto* c = static_cast<Corpus*>(h);
    std::memcpy(rows_out, c->cooc_rows.data(),
                c->cooc_rows.size() * sizeof(int32_t));
    std::memcpy(cols_out, c->cooc_cols.data(),
                c->cooc_cols.size() * sizeof(int32_t));
    std::memcpy(vals_out, c->cooc_vals.data(),
                c->cooc_vals.size() * sizeof(float));
}

}  // extern "C"
