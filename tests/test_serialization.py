"""Model serialization tests.

Mirrors the reference's serialization strategy (SURVEY.md §4): save/load
round trip for both network types, updater-state preservation
(resume-training continuity), and a committed golden file guarding the
format across versions (reference: regressiontest/RegressionTest*.java
loading zips produced by past releases)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nn.compgraph import ComputationGraph
from deeplearning4j_tpu.nn.conf import (
    BatchNormalization,
    DenseLayer,
    InputType,
    LSTM,
    MergeVertex,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils import (
    load_model,
    restore_computation_graph,
    restore_multi_layer_network,
    save_model,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _mln(updater=Updater.ADAM, seed=11):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater)
        .learning_rate(0.02)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=6, n_out=12, activation="tanh"))
        .layer(BatchNormalization(n_in=12))
        .layer(OutputLayer(n_in=12, n_out=3, activation="softmax", loss="mcxent"))
        .build()
    ).init()


def _cg(seed=13):
    return ComputationGraph(
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Updater.NESTEROVS)
        .learning_rate(0.05)
        .weight_init("xavier")
        .graph_builder()
        .add_inputs("in")
        .add_layer("a", DenseLayer(n_out=8, activation="relu"), "in")
        .add_layer("b", DenseLayer(n_out=8, activation="tanh"), "in")
        .add_vertex("m", MergeVertex(), "a", "b")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax"), "m")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(6))
        .build()
    ).init()


def _xy(n=32, nin=6, nout=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, nin)).astype(np.float32)
    y = np.zeros((n, nout), np.float32)
    y[np.arange(n), rng.integers(0, nout, n)] = 1.0
    return x, y


def test_mln_save_load_round_trip(tmp_path):
    net = _mln()
    x, y = _xy()
    net.fit(x, y, epochs=2, batch_size=16, async_prefetch=False)
    p = tmp_path / "model.zip"
    save_model(net, p)
    net2 = restore_multi_layer_network(p)
    np.testing.assert_allclose(
        np.asarray(net.output(x)), np.asarray(net2.output(x)), rtol=1e-6
    )
    # counters restored (LR schedules resume at the right iteration)
    assert net2.iteration == net.iteration
    assert net2.epoch == net.epoch
    # BN running stats restored
    for s1, s2 in zip(net.state_list, net2.state_list):
        if s1 is None:
            assert s2 is None
            continue
        for k in s1:
            np.testing.assert_allclose(np.asarray(s1[k]), np.asarray(s2[k]), rtol=1e-6)


def test_cg_save_load_round_trip(tmp_path):
    net = _cg()
    x, y = _xy()
    net.fit(x, y, epochs=2, batch_size=16, async_prefetch=False)
    p = tmp_path / "graph.zip"
    save_model(net, p)
    net2 = restore_computation_graph(p)
    np.testing.assert_allclose(
        np.asarray(net.output(x)), np.asarray(net2.output(x)), rtol=1e-6
    )


def test_resume_training_continuity(tmp_path):
    """train k steps -> save -> load -> train k more == train 2k straight
    (updater momentum preserved; reference: updaterState.bin round trip)."""
    x, y = _xy(64)
    straight = _mln()
    straight.fit(x, y, epochs=4, batch_size=16, async_prefetch=False)

    resumed = _mln()
    resumed.fit(x, y, epochs=2, batch_size=16, async_prefetch=False)
    p = tmp_path / "ckpt.zip"
    save_model(resumed, p)
    resumed2 = restore_multi_layer_network(p)
    resumed2.fit(x, y, epochs=2, batch_size=16, async_prefetch=False)

    for p1, p2 in zip(straight.params_list, resumed2.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=1e-5, atol=1e-6
            )


def test_resume_without_updater_differs(tmp_path):
    """load_updater=False resets momentum — sanity check that the updater
    state actually matters (guards against silently-empty updaterState)."""
    x, y = _xy(64)
    net = _mln(updater=Updater.NESTEROVS)
    net.fit(x, y, epochs=2, batch_size=16, async_prefetch=False)
    p = tmp_path / "ckpt.zip"
    save_model(net, p)
    with_upd = restore_multi_layer_network(p, load_updater=True)
    without = restore_multi_layer_network(p, load_updater=False)
    with_upd.fit(x, y, epochs=1, batch_size=16, async_prefetch=False)
    without.fit(x, y, epochs=1, batch_size=16, async_prefetch=False)
    diffs = [
        np.max(np.abs(np.asarray(a[k]) - np.asarray(b[k])))
        for a, b in zip(with_upd.params_list, without.params_list)
        for k in a
    ]
    assert max(diffs) > 1e-7


def test_wrong_type_restore_raises(tmp_path):
    net = _mln()
    p = tmp_path / "m.zip"
    save_model(net, p)
    with pytest.raises(ValueError, match="not a ComputationGraph"):
        restore_computation_graph(p)


def test_golden_file_regression():
    """Load the committed fixture and assert exact expected outputs —
    the cross-version format contract (reference:
    regressiontest/RegressionTest080.java)."""
    path = os.path.join(FIXTURES, "mln_adam_v1.zip")
    expected = np.load(os.path.join(FIXTURES, "mln_adam_v1_expected.npz"))
    net = load_model(path)
    x = expected["x"]
    out = np.asarray(net.output(x))
    np.testing.assert_allclose(out, expected["out"], rtol=1e-5, atol=1e-6)
    assert net.iteration == int(expected["iteration"])


def test_state_dtype_preserving_round_trip(tmp_path):
    """v2 format preserves per-leaf dtypes and catches shape drift
    (ADVICE r2: v1 forced everything through f32)."""
    import io

    import jax.numpy as jnp

    from deeplearning4j_tpu.utils.model_serializer import (
        _tree_from_npz_bytes,
        _tree_to_npz_bytes,
    )

    tree = {
        "step": jnp.asarray(3, jnp.int32),
        "m": jnp.asarray(np.arange(6, dtype=np.float32).reshape(2, 3)),
        "big": jnp.asarray(np.array([2.0**25 + 1], np.float32)),
    }
    data = _tree_to_npz_bytes(tree)
    back = _tree_from_npz_bytes(tree, data)
    assert np.asarray(back["step"]).dtype == np.int32
    assert int(back["step"]) == 3
    np.testing.assert_array_equal(np.asarray(back["m"]), np.asarray(tree["m"]))
    # shape drift is an error, not a silent misread
    bad_template = dict(tree, m=jnp.zeros((3, 2), jnp.float32))
    with pytest.raises(ValueError, match="shape"):
        _tree_from_npz_bytes(bad_template, data)


def test_updater_state_exact_round_trip(tmp_path):
    net = _mln()
    x, y = _xy()
    net.fit(x, y, epochs=2, batch_size=16, async_prefetch=False)
    p = tmp_path / "exact.zip"
    save_model(net, p)
    back = load_model(p)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(net.upd_state),
                    jax.tree_util.tree_leaves(back.upd_state)):
        assert np.asarray(a).dtype == np.asarray(b).dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(net.params()),
                                  np.asarray(back.params()))
