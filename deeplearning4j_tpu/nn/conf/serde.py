"""Polymorphic JSON serialization for config dataclasses.

Analog of the reference's Jackson-based nn/conf/serde (JSON/YAML round trip
with layer-type polymorphism). Every config dataclass registers under a
stable type tag; nested configs serialize recursively. The JSON layout —
{"type": <tag>, ...fields} — is this framework's cross-version compat
surface, guarded by regression tests the same way the reference guards
configuration.json (SURVEY.md §4 "Serialization regression tests").
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Type

_TYPE_REGISTRY: Dict[str, Type] = {}
_CLASS_TAGS: Dict[Type, str] = {}


def register_config(tag: str):
    """Class decorator: register a dataclass under a stable JSON type tag."""

    def deco(cls):
        _TYPE_REGISTRY[tag] = cls
        _CLASS_TAGS[cls] = tag
        return cls

    return deco


def config_to_dict(obj: Any) -> Any:
    """Recursively serialize a registered config dataclass to plain dicts."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        tag = _CLASS_TAGS.get(type(obj))
        out = {}
        if tag is not None:
            out["type"] = tag
        for f in dataclasses.fields(obj):
            out[f.name] = config_to_dict(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {k: config_to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [config_to_dict(v) for v in obj]
    return obj


def config_from_dict(d: Any) -> Any:
    """Inverse of config_to_dict. Dicts carrying a registered "type" tag are
    rebuilt as their dataclass; unknown tags raise (fail loudly, like the
    reference's legacy-format checks)."""
    if isinstance(d, dict):
        tag = d.get("type")
        if tag is not None and tag in _TYPE_REGISTRY:
            cls = _TYPE_REGISTRY[tag]
            field_names = {f.name for f in dataclasses.fields(cls)}
            kwargs = {
                k: config_from_dict(v)
                for k, v in d.items()
                if k != "type" and k in field_names
            }
            return cls(**kwargs)
        if tag is not None and tag not in _TYPE_REGISTRY:
            raise ValueError(f"unknown config type tag {tag!r}")
        return {k: config_from_dict(v) for k, v in d.items()}
    if isinstance(d, list):
        return [config_from_dict(v) for v in d]
    return d


def config_to_json(obj: Any, indent: int = 2) -> str:
    return json.dumps(config_to_dict(obj), indent=indent)


def config_to_yaml(obj: Any) -> str:
    """YAML serde (reference: NeuralNetConfiguration.toYaml/fromYaml —
    the same Jackson tree, different syntax). Round-trips through the
    identical tagged-dict representation as JSON."""
    import yaml

    return yaml.safe_dump(config_to_dict(obj), sort_keys=False)


def config_from_yaml(s: str) -> Any:
    import yaml

    return config_from_dict(yaml.safe_load(s))


def config_from_json(s: str) -> Any:
    return config_from_dict(json.loads(s))
