"""updaterState.bin round-trip (modelimport/dl4j.py): a model exported
mid-training and re-imported must RESUME — the next optimizer step must
produce exactly the params an uninterrupted run produces, which requires
the optimizer moments (Adam m/v, Nesterov velocity, ...), the iteration
counter (Adam bias correction + lr schedules), and the training
hyperparameters to survive the zip.

Reference contract: ModelSerializer.writeModel saveUpdater
(ModelSerializer.java:107-119) / restoreMultiLayerNetwork(file,
loadUpdater) (:148); state-view layout per BaseMultiLayerUpdater's
UpdaterBlocks (BaseMultiLayerUpdater.java:63-104)."""

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.dl4j import (
    _UPDATER_COMPONENTS,
    export_dl4j_graph,
    export_dl4j_zip,
    import_dl4j_computation_graph,
    import_dl4j_multilayer,
    restore_updater_state,
    updater_state_to_flat,
)
from deeplearning4j_tpu.nn.compgraph import ComputationGraph
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    DenseLayer,
    GravesLSTM,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _cls_data(n=32, nin=6, k=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, nin)).astype(np.float32)
    y = np.zeros((n, k), np.float32)
    y[np.arange(n), rng.integers(0, k, n)] = 1.0
    return x, y


def _mlp_net(updater="adam", seed=5):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater).learning_rate(0.05)
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=9, activation="tanh"))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _max_param_diff(a, b):
    return max(
        float(np.max(np.abs(np.asarray(pa[k]) - np.asarray(pb[k]))))
        for pa, pb in zip(a.params_list, b.params_list) for k in pa
    )


@pytest.mark.parametrize("updater", ["adam", "nesterovs", "rmsprop",
                                     "adagrad", "adamax", "adadelta"])
def test_resume_matches_uninterrupted(tmp_path, updater):
    """export mid-training -> import -> one more step == uninterrupted."""
    x, y = _cls_data()
    net = _mlp_net(updater)
    net.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)

    path = str(tmp_path / "mid.zip")
    export_dl4j_zip(net, path)
    back = import_dl4j_multilayer(path)
    assert back.iteration == net.iteration
    assert back.net_conf.updater == updater

    # the moments made the trip exactly
    a = updater_state_to_flat(net)
    b = updater_state_to_flat(back)
    np.testing.assert_allclose(a, b, atol=0, rtol=0)

    # one more epoch on both: identical trajectories
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    back.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    assert _max_param_diff(net, back) < 1e-6


def test_cold_updater_diverges(tmp_path):
    """Sanity: WITHOUT the updater state the resumed trajectory differs —
    proves the test above actually exercises the moments."""
    x, y = _cls_data()
    net = _mlp_net("adam")
    net.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)
    path = str(tmp_path / "mid.zip")
    export_dl4j_zip(net, path)
    cold = import_dl4j_multilayer(path, load_updater=False)
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    cold.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    assert _max_param_diff(net, cold) > 1e-6


def test_graves_lstm_state_layout_round_trip(tmp_path):
    """Gate-permuted + peephole-packed moment layout survives the trip."""
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater("adam").learning_rate(0.02).list()
            .layer(GravesLSTM(n_out=7, activation="tanh"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(3)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 8, 3)).astype(np.float32)
    yy = np.zeros((16, 8, 2), np.float32)
    yy[..., 0] = 1.0
    net.fit(x, yy, batch_size=16, epochs=2, async_prefetch=False)

    path = str(tmp_path / "lstm.zip")
    export_dl4j_zip(net, path)
    back = import_dl4j_multilayer(path)
    np.testing.assert_allclose(updater_state_to_flat(net),
                               updater_state_to_flat(back), atol=0, rtol=0)
    net.fit(x, yy, batch_size=16, epochs=1, async_prefetch=False)
    back.fit(x, yy, batch_size=16, epochs=1, async_prefetch=False)
    assert _max_param_diff(net, back) < 1e-6


def test_state_view_halves_are_m_then_v():
    """Pin the nd4j block layout: for a one-block Adam net, the first half
    of the view is ALL m (in flat param order), the second ALL v."""
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater("adam").learning_rate(0.05).list()
            .layer(DenseLayer(n_out=5, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(3)).build())
    net = MultiLayerNetwork(conf).init()
    x, y = _cls_data(16, 3, 2)
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    flat = updater_state_to_flat(net)
    n = net.num_params()
    assert flat.size == 2 * n
    m0 = np.asarray(net.upd_state[0]["W"]["m"]).reshape(-1, order="F")
    v0 = np.asarray(net.upd_state[0]["W"]["v"]).reshape(-1, order="F")
    np.testing.assert_allclose(flat[: m0.size], m0)
    np.testing.assert_allclose(flat[n: n + v0.size], v0)


def test_bn_mean_var_split_blocks():
    """BN running mean/var are NONE-updater params in DL4J: they carry no
    state but break block contiguity, so the layers before and after BN
    form separate [m|v] blocks rather than one."""
    net = _mlp_net("adam")
    x, y = _cls_data()
    net.fit(x, y, batch_size=32, epochs=1, async_prefetch=False)
    flat = updater_state_to_flat(net)
    sizes = [sum(int(np.prod(np.asarray(v).shape)) for v in p.values())
             for p in net.params_list]
    assert flat.size == 2 * sum(sizes)
    # block 1 = dense W+b + bn gamma+beta; its m-half must START with
    # dense W's m and the v-half with dense W's v
    blk1 = sizes[0] + sizes[1]
    mW = np.asarray(net.upd_state[0]["W"]["m"]).reshape(-1, order="F")
    vW = np.asarray(net.upd_state[0]["W"]["v"]).reshape(-1, order="F")
    np.testing.assert_allclose(flat[: mW.size], mW)
    np.testing.assert_allclose(flat[blk1: blk1 + vW.size], vW)
    # block 2 = output W+b, its own [m|v]
    mW2 = np.asarray(net.upd_state[2]["W"]["m"]).reshape(-1, order="F")
    np.testing.assert_allclose(flat[2 * blk1: 2 * blk1 + mW2.size], mW2)


def test_graph_resume_matches_uninterrupted(tmp_path):
    conf = (NeuralNetConfiguration.builder().seed(9)
            .updater("adam").learning_rate(0.03)
            .graph_builder().add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=4, activation="softmax",
                                          loss="mcxent"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(6))
            .build())
    net = ComputationGraph(conf).init()
    x, y = _cls_data()
    net.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)
    path = str(tmp_path / "graph.zip")
    export_dl4j_graph(net, path)
    back = import_dl4j_computation_graph(path)
    assert back.iteration == net.iteration
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    back.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    assert _max_param_diff(net, back) < 1e-6


def test_stateless_updater_writes_no_entry(tmp_path):
    net = _mlp_net("sgd")
    x, y = _cls_data()
    net.fit(x, y, batch_size=32, epochs=1, async_prefetch=False)
    assert updater_state_to_flat(net).size == 0
    path = str(tmp_path / "sgd.zip")
    export_dl4j_zip(net, path)
    import zipfile

    with zipfile.ZipFile(path) as zf:
        assert "updaterState.bin" not in zf.namelist()
    back = import_dl4j_multilayer(path)
    np.testing.assert_allclose(np.asarray(back.output(x)),
                               np.asarray(net.output(x)), atol=1e-6)
