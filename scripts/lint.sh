#!/usr/bin/env bash
# Concurrency/robustness lint gate (analysis/lint.py, CC001-CC006).
#
# Same gate semantics as scripts/t1.sh: the exit status reports
# REGRESSIONS, not raw findings. ERROR-severity finding NAMES (stable
# `CODE:path:scope` ids — no line numbers, so they survive unrelated
# edits) are written to an artifact ($LINT_FINDINGS_ARTIFACT, default
# /tmp/_lint_findings.txt) and diffed against the committed
# scripts/lint_baseline.txt:
#   exit 0 — no ERROR finding that is not already in the baseline
#   exit 1 — new ERROR findings (they are listed)
#   exit 2 — the linter itself failed to run
# WARNING/INFO findings never gate; see them with
#   python -m deeplearning4j_tpu.analysis.lint
set -o pipefail
cd "$(dirname "$0")/.."

artifact="${LINT_FINDINGS_ARTIFACT:-/tmp/_lint_findings.txt}"
baseline="scripts/lint_baseline.txt"

# clear any stale artifact first: a linter that crashes BEFORE writing
# must leave nothing behind for the diff to false-green against
rm -f "$artifact"
python -m deeplearning4j_tpu.analysis.lint --quiet --errors-out "$artifact"
rc=$?
if [ ! -f "$artifact" ] || [ "$rc" -gt 1 ]; then
    echo "LINT: linter failed to run (rc=$rc)"
    exit 2
fi

new_findings=$(comm -13 <(grep -v '^#' "$baseline" | sort -u) \
                        <(sort -u "$artifact"))
if [ -n "$new_findings" ]; then
    echo "LINT REGRESSIONS — ERROR findings not in $baseline:"
    echo "$new_findings"
    echo "LINT: fix them (see 'python -m deeplearning4j_tpu.analysis.lint'" \
         "for details/fix hints); only grow the baseline for a deliberate," \
         "reviewed exemption"
    exit 1
fi
echo "LINT OK: $(wc -l < "$artifact" | tr -d ' ') ERROR finding(s), all" \
     "within the baseline; artifact: $artifact"
exit 0
