"""Precision policy for TPU execution.

The reference runs f32 (f64 for gradient checks) on CPU/GPU
(GradientCheckUtil.java:77-91 forces global double precision). On TPU the
idiomatic discipline is: bf16 for matmul/conv inputs (MXU-native), f32
accumulation and parameters, f64 only on the CPU backend for numeric
gradient checking. A PrecisionPolicy captures that choice per-model.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Dtype discipline for one network.

    param_dtype:   dtype parameters are stored in (f32 default).
    compute_dtype: dtype activations/matmul operands are cast to
                   (bf16 on TPU for MXU throughput; f32 for parity tests).
    output_dtype:  dtype of network outputs/loss (f32).
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32

    def cast_input(self, x):
        return x.astype(self.compute_dtype) if x.dtype != self.compute_dtype else x

    def cast_output(self, x):
        return x.astype(self.output_dtype) if x.dtype != self.output_dtype else x


_F32 = PrecisionPolicy()
_BF16 = PrecisionPolicy(compute_dtype=jnp.bfloat16)


def default_policy() -> PrecisionPolicy:
    """Full-f32 policy — the safe default; tests and gradient checks use it."""
    return _F32


def tpu_policy() -> PrecisionPolicy:
    """bf16-compute policy — the TPU benchmark configuration."""
    return _BF16


def policy_from_name(name: str) -> PrecisionPolicy:
    name = name.lower()
    if name in ("f32", "float32", "full"):
        return _F32
    if name in ("bf16", "bfloat16", "mixed"):
        return _BF16
    raise ValueError(f"unknown precision policy: {name!r}")
