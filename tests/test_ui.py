"""Observability pipeline: codec, storage, StatsListener, UIServer.

Mirrors the reference's UI tests (TestStatsStorage + TrainModule route
coverage): train a small net with a StatsListener, assert the storage
holds real per-iteration records, serve them over the dashboard routes.
"""

import json
import urllib.request

import numpy as np

from deeplearning4j_tpu.ui import (
    FileStatsStorage,
    InMemoryStatsStorage,
    RemoteUIStatsStorageRouter,
    StatsListener,
    UIServer,
)
from deeplearning4j_tpu.ui.codec import decode_record, encode_record


def _train_with_listener(storage, n_iters=6):
    from deeplearning4j_tpu.models.lenet import lenet_conf
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = MultiLayerNetwork(lenet_conf()).init()
    net.set_collect_stats(True)
    listener = StatsListener(storage, session_id="test-session",
                             report_memory=False)
    net.set_listeners(listener)
    rng = np.random.default_rng(0)
    x = rng.random((8 * n_iters, 784), np.float32)
    y = np.zeros((8 * n_iters, 10), np.float32)
    y[np.arange(8 * n_iters), rng.integers(0, 10, 8 * n_iters)] = 1.0
    net.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)
    return net


def test_codec_round_trip():
    rec = {
        "iteration": 42, "ts": 123.5, "score": 0.75, "etl_ms": 1.5,
        "samples_per_sec": 1000.0, "epoch": 3,
        "grad_mm": {"0_W": 0.5, "0_b": 0.25},
        "hist": [1.0, 2.0, 3.0],
    }
    out = decode_record(encode_record(rec))
    assert out["iteration"] == 42
    assert abs(out["score"] - 0.75) < 1e-6
    assert abs(out["grad_mm"]["0_W"] - 0.5) < 1e-6
    assert out["hist"] == [1.0, 2.0, 3.0]
    assert out["epoch"] == 3.0


def test_stats_listener_collects_fused_stats():
    storage = InMemoryStatsStorage()
    _train_with_listener(storage)
    assert storage.list_session_ids() == ["test-session"]
    static = storage.get_static_info("test-session")
    assert static["total_params"] > 0
    assert static["model_class"] == "MultiLayerNetwork"
    ups = storage.get_updates("test-session")
    assert len(ups) == 6
    u = ups[-1]
    assert np.isfinite(u["score"])
    # fused grad/update/param mean magnitudes present and positive
    for group in ("grad_mm", "update_mm", "param_mm"):
        assert u[group], group
        assert all(v >= 0 for v in u[group].values())
    # incremental read
    later = storage.get_updates("test-session",
                                since_iteration=ups[2]["iteration"])
    assert len(later) == 3


def test_file_stats_storage_cold_read(tmp_path):
    path = str(tmp_path / "stats.bin")
    storage = FileStatsStorage(path)
    _train_with_listener(storage, n_iters=3)
    # reopen cold, as the dashboard would for a finished run
    cold = FileStatsStorage(path)
    assert cold.list_session_ids() == ["test-session"]
    assert len(cold.get_updates("test-session")) == 3
    assert cold.get_static_info("test-session")["total_params"] > 0


def _get(port, route):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{route}") as r:
        ct = r.headers.get("Content-Type", "")
        body = r.read()
    return ct, body


def test_ui_server_routes():
    storage = InMemoryStatsStorage()
    _train_with_listener(storage, n_iters=4)
    server = UIServer(storage, port=0)
    port = server.start()
    try:
        ct, body = _get(port, "/train/overview")
        assert "text/html" in ct and b"dl4j-tpu" in body
        _, body = _get(port, "/train/overview/data")
        d = json.loads(body)
        assert len(d["score"]) == 4
        assert d["session"] == "test-session"
        _, body = _get(port, "/train/model/data")
        d = json.loads(body)
        assert d["layers"], "model view should list layers"
        assert any(l["series"] for l in d["layers"])
        _, body = _get(port, "/train/system/data")
        d = json.loads(body)
        assert d["static"]["model_class"] == "MultiLayerNetwork"
        _, body = _get(port, "/train/sessions/all")
        assert json.loads(body)["sessions"] == ["test-session"]
    finally:
        server.stop()


def test_remote_router_to_ui_server():
    """Remote training process -> POST /remote -> dashboard storage
    (reference: RemoteReceiverModule + remote listeners)."""
    storage = InMemoryStatsStorage()
    server = UIServer(storage, port=0)
    port = server.start()
    try:
        router = RemoteUIStatsStorageRouter(f"http://127.0.0.1:{port}")
        _train_with_listener(router, n_iters=3)
        router.flush()
        # records crossed the HTTP boundary into the server's storage
        ups = storage.get_updates("test-session")
        assert len(ups) == 3
        assert np.isfinite(ups[-1]["score"])
        assert ups[-1]["grad_mm"]
    finally:
        server.stop()


def test_histogram_tsne_activation_modules():
    """Round-4 UI tail (reference: HistogramModule, TsneModule,
    ConvolutionalListenerModule): train a conv net with histogram +
    activation listeners, then pull all three new data routes."""
    from deeplearning4j_tpu.models.lenet import lenet_conf
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.ui import ConvolutionalIterationListener

    storage = InMemoryStatsStorage()
    net = MultiLayerNetwork(lenet_conf()).init()
    net.set_collect_stats(True)
    sl = StatsListener(storage, session_id="ui-tail", report_memory=False,
                       histogram_bins=16)
    net.set_listeners(sl, ConvolutionalIterationListener(
        storage, "ui-tail", frequency=1, max_channels=4, max_hw=8))
    rng = np.random.default_rng(1)
    x = rng.random((24, 784), np.float32)
    y = np.zeros((24, 10), np.float32)
    y[np.arange(24), rng.integers(0, 10, 24)] = 1.0
    net.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)

    server = UIServer(storage, port=0)
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    try:
        # histogram: every param of every layer, counts sum to param count
        h = json.loads(urllib.request.urlopen(
            base + "/train/histogram/data").read())
        assert h["hists"], "no histograms collected"
        some = next(iter(h["hists"].values()))
        assert len(some["edges"]) == len(some["counts"]) + 1
        n0 = int(np.prod(np.asarray(net.params_list[0]["W"]).shape))
        assert sum(h["hists"]["0_W"]["counts"]) == n0

        # activations: a grid of 2-d channel maps in [0, 1]
        a = json.loads(urllib.request.urlopen(
            base + "/train/activations/data").read())
        assert a["activations"] is not None
        chans = a["activations"]["channels"]
        assert 1 <= len(chans) <= 4
        arr = np.asarray(chans[0])
        assert arr.ndim == 2 and arr.min() >= 0.0 and arr.max() <= 1.0

        # overview still works with activation frames in the stream
        o = json.loads(urllib.request.urlopen(
            base + "/train/overview/data").read())
        assert len(o["score"]) >= 3

        # t-SNE: compute over posted vectors, then read coords back
        vecs = np.random.default_rng(2).standard_normal((30, 8)).tolist()
        words = [f"w{i}" for i in range(30)]
        req = urllib.request.Request(
            base + "/tsne/compute",
            data=json.dumps({"vectors": vecs, "words": words,
                             "perplexity": 5.0, "iters": 60}).encode(),
            headers={"Content-Type": "application/json"})
        assert json.loads(urllib.request.urlopen(req).read())["status"] == "ok"
        t = json.loads(urllib.request.urlopen(base + "/tsne/data").read())
        assert len(t["coords"]) == 30 and len(t["words"]) == 30
        assert all(len(c) == 2 for c in t["coords"])

        # the three pages render
        for page in ("/train/histogram", "/train/activations", "/tsne"):
            html = urllib.request.urlopen(base + page).read().decode()
            assert "dl4j-tpu training" in html
    finally:
        server.stop()


def test_sqlite_stats_storage(tmp_path):
    """Indexed durable storage (MapDB/J7FileStatsStorage analog): SPI
    parity with the file store + since_iteration as a range query +
    cold reopen."""
    from deeplearning4j_tpu.ui import SqliteStatsStorage

    path = str(tmp_path / "stats.sqlite")
    s = SqliteStatsStorage(path)
    s.put_static_info("sess", {"model_class": "M", "total_params": 3})
    for i in range(20):
        s.put_update("sess", {"iteration": i, "ts": float(i),
                              "score": 1.0 / (i + 1)})
    assert s.list_session_ids() == ["sess"]
    assert s.get_static_info("sess")["total_params"] == 3
    ups = s.get_updates("sess")
    assert [u["iteration"] for u in ups] == list(range(20))
    tail = s.get_updates("sess", since_iteration=15)
    assert [u["iteration"] for u in tail] == [16, 17, 18, 19]
    assert s.latest_session_id() == "sess"
    s.close()

    cold = SqliteStatsStorage(path)  # reopen: data survived
    assert len(cold.get_updates("sess")) == 20
    assert abs(cold.get_updates("sess")[3]["score"] - 0.25) < 1e-9
    cold.close()
