"""Static-analysis subsystem tests (deeplearning4j_tpu/analysis/).

Three passes, one contract each:
- shapeflow: deliberately broken configs yield their documented SF***
  finding code; the shipped resnet50/charlstm configs yield zero ERRORs.
- jaxpr audit: injected f64 constants, large folded constants, host
  callbacks, and dead params are flagged (JX***); clean nets audit clean.
- concurrency lint: one fixture per CC*** code; the committed tree has
  no ERROR finding outside scripts/lint_baseline.txt (the same
  invariant scripts/lint.sh gates in t1).
"""

from __future__ import annotations

import json
import textwrap

import numpy as np
import pytest

from deeplearning4j_tpu.analysis import (
    ERROR,
    WARNING,
    doctor_errors,
    has_errors,
    jaxpr_audit,
    preflight_report,
    shapeflow,
)
from deeplearning4j_tpu.analysis.findings import Finding, summarize
from deeplearning4j_tpu.analysis.lint import lint_paths
from deeplearning4j_tpu.analysis.lint import main as lint_main
from deeplearning4j_tpu.nn.conf import (
    ConvolutionLayer,
    DenseLayer,
    ElementWiseVertex,
    InputType,
    LayerVertex,
    MergeVertex,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration


def codes(findings):
    return [f.code for f in findings]


def errors(findings):
    return [f for f in findings if f.severity == ERROR]


# -- shapeflow: MultiLayerConfiguration --------------------------------------


def test_nin_mismatch_yields_sf001():
    conf = MultiLayerConfiguration(
        layers=[DenseLayer(n_in=10, n_out=5),
                OutputLayer(n_in=7, n_out=3)],  # 5 flows in, 7 declared
        input_type=InputType.feed_forward(10))
    fs = shapeflow.check_configuration(conf)
    assert [f.code for f in errors(fs)] == ["SF001"]
    # mapped to the offending layer, and the fix names the right number
    assert "layer[1]" in errors(fs)[0].location
    assert "5" in errors(fs)[0].message


def test_unset_nout_yields_sf001():
    conf = MultiLayerConfiguration(
        layers=[DenseLayer(n_in=4, n_out=0),
                OutputLayer(n_in=0, n_out=3)],
        input_type=InputType.feed_forward(4))
    fs = shapeflow.check_configuration(conf)
    assert "SF001" in [f.code for f in errors(fs)]


def test_no_inputtype_fallback_skips_conv_producers():
    """Without an InputType, n_in can only be compared along a pure
    dense chain: a conv's n_out is CHANNELS, so a correctly wired
    flattened dense (n_in = h*w*c) must not be flagged."""
    conf = MultiLayerConfiguration(
        layers=[ConvolutionLayer(n_in=3, n_out=8),
                DenseLayer(n_in=288, n_out=10),  # 8ch * 6x6 flattened
                OutputLayer(n_in=10, n_out=3)])
    fs = shapeflow.check_configuration(conf)
    assert "SF001" not in [f.code for f in errors(fs)]
    # but a genuinely miswired dense->dense chain still is flagged
    conf = MultiLayerConfiguration(
        layers=[DenseLayer(n_in=4, n_out=8),
                OutputLayer(n_in=9, n_out=3)])
    fs = shapeflow.check_configuration(conf)
    assert "SF001" in [f.code for f in errors(fs)]


def test_family_mismatch_yields_sf002():
    # conv layer fed feed-forward input with no preprocessor
    conf = MultiLayerConfiguration(
        layers=[ConvolutionLayer(n_in=3, n_out=4),
                OutputLayer(n_in=4, n_out=3)],
        input_type=InputType.feed_forward(12))
    fs = shapeflow.check_configuration(conf)
    assert "SF002" in [f.code for f in errors(fs)]


def test_missing_loss_head_yields_sf007_warning():
    conf = MultiLayerConfiguration(
        layers=[DenseLayer(n_in=4, n_out=2)],
        input_type=InputType.feed_forward(4))
    fs = shapeflow.check_configuration(conf)
    assert not errors(fs)
    assert "SF007" in codes(fs)


def test_builder_built_configs_are_clean():
    from deeplearning4j_tpu.models.charlstm import char_lstm_conf
    from deeplearning4j_tpu.models.resnet import (
        resnet50_conf,
        tiny_resnet_conf,
    )

    for conf in (char_lstm_conf(), resnet50_conf(), tiny_resnet_conf()):
        fs = shapeflow.check_configuration(conf)
        assert not errors(fs), [f.format() for f in fs]
        assert not fs  # clean means CLEAN: zero findings at any severity


def test_bf16_promotion_point_is_informational():
    from deeplearning4j_tpu.models.charlstm import char_lstm_conf

    fs = shapeflow.check_configuration(char_lstm_conf(precision="bf16"))
    assert codes(fs) == ["SF006"]
    assert not has_errors(fs)


# -- shapeflow: ComputationGraphConfiguration --------------------------------


def _graph_builder(*input_types, names=("in",)):
    gb = NeuralNetConfiguration.builder().graph_builder().add_inputs(*names)
    if input_types:
        gb.set_input_types(*input_types)
    return gb


def test_merge_fanin_conflict_yields_sf003():
    gb = _graph_builder(InputType.convolutional(8, 8, 3),
                        InputType.convolutional(4, 4, 3),
                        names=("a", "b"))
    gb.add_vertex("m", MergeVertex(), "a", "b")
    gb.add_layer("out", OutputLayer(n_out=2), "m")
    gb.set_outputs("out")
    fs = shapeflow.check_configuration(gb.build())
    sf3 = [f for f in errors(fs) if f.code == "SF003"]
    assert sf3 and sf3[0].location == "vertex:m"


def test_dead_vertex_yields_sf004():
    gb = _graph_builder(InputType.feed_forward(6))
    gb.add_layer("h", DenseLayer(n_out=4), "in")
    gb.add_layer("side", DenseLayer(n_out=3), "in")  # feeds nothing
    gb.add_layer("out", OutputLayer(n_out=2), "h")
    gb.set_outputs("out")
    fs = shapeflow.check_configuration(gb.build())
    dead = [f for f in fs if f.code == "SF004"]
    assert dead and dead[0].severity == WARNING
    assert dead[0].location == "vertex:side"


def test_cyclic_graph_yields_sf004_error():
    conf = ComputationGraphConfiguration(
        inputs=["in"], outputs=["out"],
        vertices={"a": LayerVertex(layer=DenseLayer(n_in=4, n_out=4)),
                  "out": LayerVertex(layer=OutputLayer(n_in=4, n_out=2))},
        vertex_inputs={"a": ["a"], "out": ["a"]})
    fs = shapeflow.check_configuration(conf)
    assert [f.code for f in errors(fs)] == ["SF004"]


def test_subset_out_of_channel_range_yields_sf005():
    """SubsetVertex slices the LAST axis — channels for cnn input; a
    bound inside h*w*c but outside the channel count is the bug."""
    from deeplearning4j_tpu.nn.conf import SubsetVertex

    gb = _graph_builder(InputType.convolutional(8, 8, 4))
    gb.add_vertex("sub", SubsetVertex(from_=0, to=10), "in")  # 4 channels!
    gb.add_layer("out", OutputLayer(n_out=2), "sub")
    gb.set_outputs("out")
    fs = shapeflow.check_configuration(gb.build())
    assert "SF005" in [f.code for f in errors(fs)]


def test_elementwise_shape_conflict_yields_sf005():
    gb = _graph_builder(InputType.feed_forward(6))
    gb.add_layer("a", DenseLayer(n_out=4), "in")
    gb.add_layer("b", DenseLayer(n_out=5), "in")
    gb.add_vertex("add", ElementWiseVertex(op="add"), "a", "b")
    gb.add_layer("out", OutputLayer(n_out=2), "add")
    gb.set_outputs("out")
    fs = shapeflow.check_configuration(gb.build())
    assert "SF005" in [f.code for f in errors(fs)]


# -- jaxpr audit --------------------------------------------------------------


def test_injected_f64_constant_yields_jx001():
    from deeplearning4j_tpu.train.gradientcheck import enable_x64

    with enable_x64():
        c64 = np.ones(3, np.float64)
        fs = jaxpr_audit.audit_fn(lambda x: x + c64,
                                  np.ones(3, np.float32))
    jx1 = [f for f in fs if f.code == "JX001"]
    assert jx1 and jx1[0].severity == ERROR


def test_large_folded_constant_yields_jx003():
    big = np.ones((600, 600), np.float32)  # 1.44 MiB closure constant
    fs = jaxpr_audit.audit_fn(lambda x: x + big,
                              np.ones((600, 600), np.float32))
    assert "JX003" in codes(fs)
    # passing it as an argument instead is the fix — and is clean
    fs = jaxpr_audit.audit_fn(lambda x, c: x + c,
                              np.ones((600, 600), np.float32), big)
    assert "JX003" not in codes(fs)


def test_host_callback_yields_jx004():
    import jax

    def fn(x):
        jax.debug.print("x={x}", x=x)
        return x * 2.0

    fs = jaxpr_audit.audit_fn(fn, np.ones(3, np.float32))
    assert "JX004" in codes(fs)


def test_dead_input_yields_jx005():
    fs = jaxpr_audit.audit_fn(lambda a, b: a * 2.0,
                              np.ones(3, np.float32),
                              np.ones(3, np.float32))
    jx5 = [f for f in fs if f.code == "JX005"]
    assert len(jx5) == 1 and "arg[1]" in jx5[0].name


def test_dead_param_in_graph_yields_jx005():
    """A dead vertex's weights have no cotangent path — the auditor
    names the vertex and the param."""
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph

    gb = _graph_builder(InputType.feed_forward(6))
    gb.add_layer("h", DenseLayer(n_out=4), "in")
    gb.add_layer("side", DenseLayer(n_out=3), "in")
    gb.add_layer("out", OutputLayer(n_out=2), "h")
    gb.set_outputs("out")
    net = ComputationGraph(gb.build()).init()
    fs = jaxpr_audit.audit_network(net)
    assert sorted(f.name for f in fs if f.code == "JX005") == [
        "JX005:param:side/W", "JX005:param:side/b"]


def test_clean_networks_audit_clean():
    from deeplearning4j_tpu.models.charlstm import char_lstm_network
    from deeplearning4j_tpu.models.resnet import tiny_resnet_conf
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph

    lstm = char_lstm_network(vocab_size=11, hidden=8, layers=1)
    assert jaxpr_audit.audit_network(lstm, timesteps=6) == []
    tiny = ComputationGraph(tiny_resnet_conf()).init()
    assert jaxpr_audit.audit_network(tiny) == []
    # net.doctor() = shapeflow + audit, end to end
    assert lstm.doctor(timesteps=6) == []


def test_donation_check():
    assert jaxpr_audit.check_donation((0, 2), backend="tpu") == []
    assert jaxpr_audit.check_donation((), backend="cpu") == []
    fs = jaxpr_audit.check_donation((), backend="tpu")
    assert [f.code for f in fs] == ["JX006"]


# -- concurrency lint ---------------------------------------------------------


_BAD_MODULE = textwrap.dedent("""\
    import queue
    import threading
    import time

    q = queue.Queue(maxsize=2)


    def wait_until(timeout):
        deadline = time.time() + timeout
        return deadline


    def worker():
        while True:
            try:
                item = q.get()
            except:
                pass
            print(item)


    def start():
        t = threading.Thread(target=worker)
        t.start()
        q.put(1)


    class A:
        def __init__(self):
            self._lock = threading.Lock()
            self._stats_lock = threading.Lock()

        def f(self):
            with self._lock:
                with self._stats_lock:
                    pass

        def g(self):
            with self._stats_lock:
                with self._lock:
                    pass
    """)


@pytest.fixture
def bad_module(tmp_path):
    p = tmp_path / "badmod.py"
    p.write_text(_BAD_MODULE)
    return p


def test_lint_flags_every_code_once(bad_module):
    fs = lint_paths([str(bad_module)], base_dir=str(bad_module.parent))
    got = sorted(set(codes(fs)))
    assert got == ["CC001", "CC002", "CC003", "CC004", "CC005", "CC006",
                   "CC007"]
    # stable names: scope-qualified, no line numbers
    names = {f.name for f in fs}
    assert "CC001:badmod.py:worker" in names
    assert "CC002:badmod.py:start" in names  # the timeout-less q.put(1)
    assert any(n.startswith("CC005:") for n in names)
    assert "CC007:badmod.py:wait_until" in names


def test_lint_accepts_the_sanctioned_shapes(tmp_path):
    p = tmp_path / "goodmod.py"
    p.write_text(textwrap.dedent("""\
        import queue
        import threading

        from deeplearning4j_tpu.utils.concurrency import (
            get_abortable,
            put_abortable,
        )

        q = queue.Queue(maxsize=2)
        stop = threading.Event()


        def worker():
            while True:
                try:
                    item = get_abortable(q, stop)
                except Exception:
                    return
                q.put(item, timeout=0.5)


        def start():
            t = threading.Thread(target=worker, daemon=True,
                                 name="dl4j-test-worker")
            t.start()
            put_abortable(q, 1, stop)
            q.put_nowait(2)
            q.put(3, block=False)  # cannot wedge: raises Full immediately
        """))
    assert lint_paths([str(p)], base_dir=str(tmp_path)) == []


def test_lint_cc007_walltime_deadlines(tmp_path):
    """CC007 fires only on wall-clock DEADLINE arithmetic: monotonic
    deadlines and plain timestamping both stay legal."""
    p = tmp_path / "clocks.py"
    p.write_text(textwrap.dedent("""\
        import time


        def legal(budget):
            deadline = time.monotonic() + budget  # sanctioned clock
            meta = {"ts": time.time()}            # timestamping
            wall = time.time()                    # no deadline words
            return deadline, meta, wall


        def bad_expiry():
            expires_at = time.time() + 60.0
            return expires_at


        def bad_poll(timeout):
            while time.time() < timeout:
                pass
        """))
    fs = lint_paths([str(p)], base_dir=str(tmp_path))
    assert sorted(f.name for f in fs) == [
        "CC007:clocks.py:bad_expiry", "CC007:clocks.py:bad_poll"]


def test_lint_str_join_does_not_mask_cc004(tmp_path):
    """str.join in the same function must not count as joining the
    thread — only thread-ish receivers satisfy CC004."""
    p = tmp_path / "joiner.py"
    p.write_text(textwrap.dedent("""\
        import threading


        def start(names):
            label = ",".join(names)
            t = threading.Thread(target=print, name="dl4j-x-" + label)
            t.start()
        """))
    fs = lint_paths([str(p)], base_dir=str(tmp_path))
    assert "CC004" in codes(fs)
    # a real join of the thread variable satisfies it
    p.write_text(textwrap.dedent("""\
        import threading


        def start(names):
            label = ",".join(names)
            t = threading.Thread(target=print, name="dl4j-x-" + label)
            t.start()
            t.join()
        """))
    assert "CC004" not in codes(lint_paths([str(p)],
                                           base_dir=str(tmp_path)))


def test_lint_positional_block_forms(tmp_path):
    """q.put(item, True) blocks with no timeout -> CC002; q.get(False)
    cannot block -> clean."""
    p = tmp_path / "posargs.py"
    p.write_text(textwrap.dedent("""\
        import queue
        import threading

        q = queue.Queue(maxsize=2)


        def f():
            q.put(1, True)


        def g():
            return q.get(False)
        """))
    fs = lint_paths([str(p)], base_dir=str(tmp_path))
    names = {f.name for f in fs if f.code == "CC002"}
    assert names == {"CC002:posargs.py:f"}


def test_lint_lock_order_cycle_needs_conflicting_orders(tmp_path):
    # consistent ordering across call sites: edges, but no cycle
    p = tmp_path / "ordered.py"
    p.write_text(textwrap.dedent("""\
        import threading

        a = threading.Lock()
        b_lock = threading.Lock()


        def f():
            with a:
                with b_lock:
                    pass


        def g():
            with a:
                with b_lock:
                    pass
        """))
    assert "CC005" not in codes(lint_paths([str(p)],
                                           base_dir=str(tmp_path)))


def test_committed_tree_is_lint_clean_modulo_baseline():
    """THE gate invariant scripts/lint.sh enforces in t1: no ERROR
    finding outside scripts/lint_baseline.txt on the committed tree."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fs = lint_paths([os.path.join(root, "deeplearning4j_tpu"),
                     os.path.join(root, "bench.py")], base_dir=root)
    with open(os.path.join(root, "scripts", "lint_baseline.txt")) as f:
        allowed = {ln.strip() for ln in f
                   if ln.strip() and not ln.startswith("#")}
    new = [f.name for f in errors(fs) if f.name not in allowed]
    assert not new, f"lint regressions vs scripts/lint_baseline.txt: {new}"


def test_lint_main_baseline_gate(bad_module, tmp_path):
    """Introducing a bare except / timeout-less put fails the gate
    (exit 1); the committed baseline keeps the committed tree green."""
    baseline = tmp_path / "baseline.txt"
    baseline.write_text("# nothing grandfathered\n")
    rc = lint_main(["--quiet", "--baseline", str(baseline),
                    str(bad_module)])
    assert rc == 1
    # grandfathering exactly today's names turns the same tree green
    fs = lint_paths([str(bad_module)], base_dir=str(bad_module.parent))
    names = sorted({f.name for f in errors(fs)})
    # names are relative to CWD in main(); regenerate from there
    fs_cwd = lint_paths([str(bad_module)])
    baseline.write_text("".join(
        sorted(f.name + "\n" for f in errors(fs_cwd))))
    assert names  # sanity: the fixture does produce errors
    rc = lint_main(["--quiet", "--baseline", str(baseline),
                    str(bad_module)])
    assert rc == 0


# -- doctor / CLI / bench wiring ----------------------------------------------


def test_doctor_never_raises_on_warning_grade_configs():
    """A config whose only defect is warning-grade (no loss head) makes
    the loss trace fail — the doctor must report that as a finding, not
    crash (the no-raise contract)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().list()
            .layer(DenseLayer(n_out=4))
            .set_input_type(InputType.feed_forward(3))
            .build())
    fs = MultiLayerNetwork(conf).init().doctor()
    assert "SF007" in codes(fs)
    assert "JX000" in codes(fs)  # trace failure surfaced as a finding
    assert not has_errors(fs)


def test_cli_doctor_clean_presets(capsys):
    from deeplearning4j_tpu.cli import main as cli_main

    rc = cli_main(["doctor", "--preset", "tiny_resnet", "--json", "-"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"] and out["errors"] == 0
    # resnet50 topology itself (config pass; small image keeps init cheap)
    rc = cli_main(["doctor", "--preset", "resnet50", "--image-size", "32",
                   "--classes", "10", "--no-jaxpr"])
    assert rc == 0


def test_cli_doctor_charlstm_clean():
    from deeplearning4j_tpu.cli import main as cli_main

    assert cli_main(["doctor", "--preset", "charlstm"]) == 0


def test_cli_lint_exits_nonzero_on_errors(bad_module, capsys):
    from deeplearning4j_tpu.cli import main as cli_main

    rc = cli_main(["lint", "--json", "-", str(bad_module)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and not out["ok"] and out["errors"] > 0


def test_bench_refuses_to_headline_broken_model():
    from bench import _doctor_refusal

    broken = MultiLayerConfiguration(
        layers=[DenseLayer(n_in=10, n_out=5),
                OutputLayer(n_in=7, n_out=3)],
        input_type=InputType.feed_forward(10))
    refusal = _doctor_refusal(broken, "images/sec/chip")
    assert refusal is not None
    assert refusal["value"] is None
    assert any("SF001" in e for e in refusal["doctor_errors"])

    from deeplearning4j_tpu.models.charlstm import char_lstm_conf

    assert _doctor_refusal(char_lstm_conf(), "tokens/sec/chip") is None


def test_doctor_errors_and_preflight_report():
    broken = MultiLayerConfiguration(
        layers=[DenseLayer(n_in=10, n_out=5),
                OutputLayer(n_in=7, n_out=3)],
        input_type=InputType.feed_forward(10))
    errs = doctor_errors(broken)
    assert [f.code for f in errs] == ["SF001"]
    # preflight logs and returns, never raises — even on garbage
    assert preflight_report(broken, origin="test.zip")
    assert preflight_report(object(), origin="junk") == []


def test_import_preflight_rides_the_dl4j_import_path(tmp_path):
    """The dl4j model-import path attaches the free pre-flight report."""
    from deeplearning4j_tpu.modelimport.dl4j import (
        export_dl4j_zip,
        import_dl4j_multilayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(7).list()
            .layer(DenseLayer(n_out=5, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    path = str(tmp_path / "m.zip")
    export_dl4j_zip(net, path)
    imported = import_dl4j_multilayer(path)
    assert imported.import_preflight == []  # clean model, clean report


def test_findings_summarize_and_name_stability():
    f = Finding("SF001", ERROR, "layer[1]:out", "boom")
    assert f.name == "SF001:layer[1]:out"
    s = summarize([f])
    assert s["errors"] == 1 and not s["ok"]
    assert s["findings"][0]["code"] == "SF001"
