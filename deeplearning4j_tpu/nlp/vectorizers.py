"""Bag-of-words / TF-IDF text vectorizers.

Analog of the reference's bagofwords/vectorizer/ (BaseTextVectorizer +
BagOfWordsVectorizer + TfidfVectorizer): build a vocabulary (with
document frequencies) over a corpus, then turn any text into a
[1, vocab] feature row — counts for bag-of-words, tf*idf for TF-IDF —
and (text, label) pairs into DataSets for the training stack.

Formulas pinned to the reference: tf = count / documentLength
(TfidfVectorizer.java tfForWord), idf = log10(totalDocs / docsWithWord)
(util/MathUtils.java:258 idf, 0 when no documents), score = tf * idf.
One deliberate deviation: the reference's BagOfWordsVectorizer.transform
writes the CORPUS-level frequency at each index
(BagOfWordsVectorizer.java:77 wordFrequency), which makes every document
containing a word score it identically; here bag-of-words is the
standard per-document count, which is what every consumer of a BoW
vector expects.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache


class LabelsSource:
    """Stable label -> index mapping (reference: text/documentiterator/
    LabelsSource.java)."""

    def __init__(self, labels: Optional[Sequence[str]] = None):
        self._labels: List[str] = []
        self._index = {}
        for l in labels or []:
            self.store(l)

    def store(self, label: str) -> int:
        if label not in self._index:
            self._index[label] = len(self._labels)
            self._labels.append(label)
        return self._index[label]

    def index_of(self, label: str) -> int:
        return self._index.get(label, -1)

    def labels(self) -> List[str]:
        return list(self._labels)

    def size(self) -> int:
        return len(self._labels)


class BaseTextVectorizer:
    """Shared vocab construction: tokenize every document, count corpus
    and document frequencies, keep words with count >= min_word_frequency
    in (count desc, word asc) order — the VocabConstructor contract."""

    def __init__(self, *, min_word_frequency: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Iterable[str] = ()):
        self.min_word_frequency = int(min_word_frequency)
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.stop_words = set(stop_words)
        self.vocab: Optional[VocabCache] = None
        self.doc_frequencies: Optional[np.ndarray] = None  # [V] int64
        self.total_docs = 0
        self.labels_source = LabelsSource()

    def tokenize(self, text: str) -> List[str]:
        toks = self.tokenizer_factory.create(text).get_tokens()
        return [t for t in toks if t and t not in self.stop_words]

    def fit(self, documents: Iterable[str],
            labels: Optional[Iterable[str]] = None) -> "BaseTextVectorizer":
        counts: Counter = Counter()
        doc_counts: Counter = Counter()
        n_docs = 0
        for doc in documents:
            toks = self.tokenize(doc)
            counts.update(toks)
            doc_counts.update(set(toks))
            n_docs += 1
        vocab = VocabCache()
        kept = sorted(
            (w for w, c in counts.items() if c >= self.min_word_frequency),
            key=lambda w: (-counts[w], w))
        for w in kept:
            vocab.add(w, counts[w])
        self.vocab = vocab
        self.total_docs = n_docs
        self.doc_frequencies = np.asarray(
            [doc_counts[w] for w in kept], np.int64)
        for l in labels or []:
            self.labels_source.store(l)
        return self

    # -- per-document weights (subclass hook) --------------------------------

    def _weight(self, count: int, doc_len: int, word_index: int) -> float:
        raise NotImplementedError

    def transform(self, text_or_tokens) -> np.ndarray:
        """One document -> [1, vocab] row."""
        if self.vocab is None:
            raise ValueError("vectorizer not fitted")
        toks = (self.tokenize(text_or_tokens)
                if isinstance(text_or_tokens, str) else list(text_or_tokens))
        out = np.zeros((1, self.vocab.num_words()), np.float32)
        counts = Counter(toks)
        for w, c in counts.items():
            idx = self.vocab.index_of(w)
            if idx >= 0:
                out[0, idx] = self._weight(c, len(toks), idx)
        return out

    def vectorize(self, text: str, label: str) -> DataSet:
        """(text, label) -> DataSet with a one-hot label row (reference:
        TfidfVectorizer.vectorize). The label space is FIXED by fit(...,
        labels=...): every DataSet gets the same label width, so batches
        stack; an unknown label is an error, not a silent widening."""
        x = self.transform(text)
        if self.labels_source.size() == 0:
            raise ValueError(
                "no label space — pass labels=[...] to fit() before "
                "vectorize()")
        li = self.labels_source.index_of(label)
        if li < 0:
            raise ValueError(
                f"unknown label {label!r}; known: "
                f"{self.labels_source.labels()}")
        y = np.zeros((1, self.labels_source.size()), np.float32)
        y[0, li] = 1.0
        return DataSet(x, y)

    def fit_transform(self, documents: Sequence[str]) -> np.ndarray:
        self.fit(documents)
        return np.concatenate([self.transform(d) for d in documents], axis=0)


class BagOfWordsVectorizer(BaseTextVectorizer):
    """Per-document term counts (see module docstring for the deliberate
    deviation from the reference's corpus-frequency quirk)."""

    def _weight(self, count, doc_len, word_index):
        return float(count)


class TfidfVectorizer(BaseTextVectorizer):
    """tf * idf with the reference's exact formulas."""

    def tf(self, count: int, doc_len: int) -> float:
        return count / doc_len if doc_len else 0.0

    def idf(self, word_index: int) -> float:
        if self.total_docs == 0:
            return 0.0
        df = int(self.doc_frequencies[word_index])
        if df == 0:
            return 0.0
        return math.log10(self.total_docs / df)

    def _weight(self, count, doc_len, word_index):
        return self.tf(count, doc_len) * self.idf(word_index)
