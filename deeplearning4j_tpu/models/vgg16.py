"""VGG16 via the Keras importer (BASELINE.md workload 5).

The reference ships VGG16 as a Keras-1.x import target
(trainedmodels/TrainedModels.java VGG16 + KerasModelImport); here the same
architecture is emitted as a Keras 1.x ``model_config`` JSON and routed
through the native importer (deeplearning4j_tpu/modelimport/keras.py), so
the benchmark exercises the real import path end to end.

Simonyan & Zisserman configuration D: 13 conv3x3 (64,64 / 128,128 /
256x3 / 512x3 / 512x3) with 2x2 maxpool between blocks, then
4096-4096-1000 dense.
"""

from __future__ import annotations

import json


def vgg16_keras_config(num_classes: int = 1000, image_size: int = 224) -> str:
    """Keras 1.x Sequential model_config JSON for VGG16 (tf dim ordering)."""
    layers = []
    widths = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
    conv_idx = pool_idx = 0
    first = True
    for block_width, n_convs in widths:
        for _ in range(n_convs):
            conv_idx += 1
            cfg = {
                "name": f"convolution2d_{conv_idx}",
                "nb_filter": block_width,
                "nb_row": 3,
                "nb_col": 3,
                "border_mode": "same",
                "subsample": [1, 1],
                "dim_ordering": "tf",
                "activation": "relu",
                "init": "glorot_uniform",
            }
            if first:
                cfg["batch_input_shape"] = [None, image_size, image_size, 3]
                first = False
            layers.append({"class_name": "Convolution2D", "config": cfg})
        pool_idx += 1
        layers.append({
            "class_name": "MaxPooling2D",
            "config": {
                "name": f"maxpooling2d_{pool_idx}",
                "pool_size": [2, 2],
                "strides": [2, 2],
                "border_mode": "valid",
                "dim_ordering": "tf",
            },
        })
    layers.append({"class_name": "Flatten", "config": {"name": "flatten_1"}})
    for i, width in enumerate((4096, 4096), start=1):
        layers.append({
            "class_name": "Dense",
            "config": {
                "name": f"dense_{i}",
                "output_dim": width,
                "activation": "relu",
                "init": "glorot_uniform",
            },
        })
    layers.append({
        "class_name": "Dense",
        "config": {
            "name": "dense_3",
            "output_dim": num_classes,
            "activation": "softmax",
            "init": "glorot_uniform",
        },
    })
    return json.dumps({"class_name": "Sequential", "config": layers})


def vgg16_conf(num_classes: int = 1000, image_size: int = 224,
               precision: str = "bf16"):
    """MultiLayerConfiguration for VGG16, built THROUGH the Keras importer
    (the import path is the workload, matching the baseline's
    'VGG16-via-Keras-import')."""
    from deeplearning4j_tpu.modelimport.keras import import_keras_sequential_config

    tc = json.dumps({"loss": "categorical_crossentropy",
                     "optimizer": {"name": "sgd"}})
    conf, _ = import_keras_sequential_config(
        vgg16_keras_config(num_classes, image_size), tc, precision=precision,
    )
    return conf
