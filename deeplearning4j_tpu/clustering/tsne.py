"""t-SNE dimensionality reduction, fully device-side (reference:
plot/BarnesHutTsne.java, 858 LoC, and plot/Tsne.java — perplexity search,
early exaggeration, momentum + per-parameter gains).

TPU-first redesign: the reference approximates the N-body repulsion with a
Barnes-Hut quadtree on the CPU (O(N log N) with terrible constants and no
vectorization). On TPU the exact O(N^2) formulation is a pair of [N, N]
matmul/softmax blocks that ride the MXU — faster than host Barnes-Hut for
every N the UI t-SNE tab realistically serves (<= ~50k points), and exact.
The full gradient loop runs inside one jitted lax.fori_loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from functools import partial


def _pairwise_sq(x):
    x2 = jnp.sum(x * x, axis=1)
    d2 = x2[:, None] + x2[None, :] - 2.0 * (x @ x.T)
    return jnp.maximum(d2, 0.0)


@jax.jit
def _cond_probs(d2, beta):
    """Row-wise conditional p_{j|i} for precision vector beta, diag zeroed."""
    n = d2.shape[0]
    logits = -d2 * beta[:, None]
    logits = jnp.where(jnp.eye(n, dtype=bool), -jnp.inf, logits)
    p = jax.nn.softmax(logits, axis=1)
    # per-row Shannon entropy -> perplexity = 2^H
    h = -jnp.sum(jnp.where(p > 0, p * jnp.log2(p + 1e-30), 0.0), axis=1)
    return p, h


@partial(jax.jit, static_argnums=(2,))
def _binary_search_beta(d2, target_h, iters=40):
    """Vectorized per-point precision search matching log2(perplexity)."""
    n = d2.shape[0]

    def body(_, carry):
        beta, lo, hi = carry
        _, h = _cond_probs(d2, beta)
        too_high = h > target_h  # entropy too high -> sharpen (raise beta)
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2.0,
                         jnp.where(jnp.isinf(lo), beta / 2.0,
                                   0.5 * (lo + hi)))
        # lo is only -inf before the first time entropy was too high
        beta = jnp.maximum(beta, 1e-12)
        return beta, lo, hi

    beta0 = jnp.ones((n,))
    lo0 = jnp.full((n,), -jnp.inf)
    hi0 = jnp.full((n,), jnp.inf)
    beta, _, _ = jax.lax.fori_loop(0, iters, body, (beta0, lo0, hi0))
    p, _ = _cond_probs(d2, beta)
    return p


@partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _tsne_run(p_sym, y0, n_iter, stop_lying_iter, momentum_switch, lr):
    """Gradient loop: KL(P||Q) descent with gains + momentum (the
    reference's update schedule: early exaggeration 12x until
    stop_lying_iter, momentum 0.5 -> 0.8 at momentum_switch)."""
    n = y0.shape[0]
    eye = jnp.eye(n, dtype=bool)

    def step(i, carry):
        y, vel, gains = carry
        d2 = _pairwise_sq(y)
        num = 1.0 / (1.0 + d2)          # student-t kernel
        num = jnp.where(eye, 0.0, num)
        q = num / jnp.maximum(jnp.sum(num), 1e-12)
        exaggeration = jnp.where(i < stop_lying_iter, 12.0, 1.0)
        pq = (exaggeration * p_sym - q) * num       # [n, n]
        grad = 4.0 * (jnp.diag(jnp.sum(pq, axis=1)) - pq) @ y
        momentum = jnp.where(i < momentum_switch, 0.5, 0.8)
        same_sign = jnp.sign(grad) == jnp.sign(vel)
        gains = jnp.maximum(
            jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
        vel = momentum * vel - lr * gains * grad
        y = y + vel
        y = y - jnp.mean(y, axis=0, keepdims=True)
        return y, vel, gains

    y, _, _ = jax.lax.fori_loop(
        0, n_iter, step,
        (y0, jnp.zeros_like(y0), jnp.ones_like(y0)))
    return y


@jax.jit
def _kl_divergence(p_sym, y):
    n = y.shape[0]
    eye = jnp.eye(n, dtype=bool)
    num = 1.0 / (1.0 + _pairwise_sq(y))
    num = jnp.where(eye, 0.0, num)
    q = num / jnp.maximum(jnp.sum(num), 1e-12)
    return jnp.sum(jnp.where(p_sym > 0,
                             p_sym * jnp.log((p_sym + 1e-12) / (q + 1e-12)),
                             0.0))


class Tsne:
    """Tsne(n_components=2, perplexity=30, n_iter=1000).fit_transform(X).

    ``theta`` is accepted for reference-API compatibility
    (BarnesHutTsne's approximation knob) and ignored: the device-exact
    path needs no approximation at dashboard scales.
    """

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 1000,
                 stop_lying_iteration: int = 250,
                 momentum_switch_iteration: int = 250,
                 theta: float = 0.5, seed: int = 0):
        del theta
        self.n_components = int(n_components)
        self.perplexity = float(perplexity)
        self.learning_rate = float(learning_rate)
        self.n_iter = int(n_iter)
        self.stop_lying_iteration = int(stop_lying_iteration)
        self.momentum_switch_iteration = int(momentum_switch_iteration)
        self.seed = seed
        self.kl_: float = float("nan")

    def fit_transform(self, x: np.ndarray) -> np.ndarray:
        x = jnp.asarray(x, jnp.float32)
        n = x.shape[0]
        if self.perplexity >= n:
            raise ValueError(f"perplexity {self.perplexity} >= n {n}")
        d2 = _pairwise_sq(x)
        target_h = jnp.full((n,), np.log2(self.perplexity))
        p = _binary_search_beta(d2, target_h)
        p_sym = (p + p.T) / (2.0 * n)
        key = jax.random.PRNGKey(self.seed)
        y0 = 1e-4 * jax.random.normal(key, (n, self.n_components))
        y = _tsne_run(p_sym, y0, self.n_iter, self.stop_lying_iteration,
                      self.momentum_switch_iteration, self.learning_rate)
        self.kl_ = float(_kl_divergence(p_sym, y))
        return np.asarray(y)
