"""ComputationGraph engine tests.

Mirrors the reference's graph test coverage (SURVEY.md §4:
deeplearning4j-core/src/test/.../nn/graph/ +
gradientcheck/GradientCheckTestsComputationGraph.java): vertex-type
semantics, topo order, multi-input/multi-output training, fan-out gradient
accumulation, serde round trip, and gradient checks on small DAGs.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import MultiDataSet
from deeplearning4j_tpu.nn.compgraph import ComputationGraph
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    DuplicateToTimeSeriesVertex,
    ElementWiseVertex,
    InputType,
    L2NormalizeVertex,
    L2Vertex,
    LastTimeStepVertex,
    LSTM,
    MergeVertex,
    NeuralNetConfiguration,
    OutputLayer,
    ReshapeVertex,
    RnnOutputLayer,
    ScaleVertex,
    ShiftVertex,
    StackVertex,
    SubsetVertex,
    UnstackVertex,
)
from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
from deeplearning4j_tpu.train.gradientcheck import check_gradients_graph


def _gb(seed=7, lr=0.05, updater="sgd"):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater)
        .learning_rate(lr)
        .weight_init("xavier")
        .graph_builder()
    )


def _xy(n=16, nin=8, nout=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, nin)).astype(np.float32)
    y = np.zeros((n, nout), np.float32)
    y[np.arange(n), rng.integers(0, nout, n)] = 1.0
    return x, y


# -- topology / build --------------------------------------------------------

def test_topological_order_diamond():
    conf = (
        _gb()
        .add_inputs("in")
        .add_layer("a", DenseLayer(n_out=4, activation="tanh"), "in")
        .add_layer("b", DenseLayer(n_out=4, activation="tanh"), "in")
        .add_vertex("m", MergeVertex(), "a", "b")
        .add_layer("out", OutputLayer(n_out=2, activation="softmax"), "m")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(8))
        .build()
    )
    order = conf.topological_order()
    assert order.index("in") < order.index("a")
    assert order.index("a") < order.index("m")
    assert order.index("b") < order.index("m")
    assert order.index("m") < order.index("out")
    # shape inference wired n_in through the merge
    assert conf.vertices["out"].layer.n_in == 8


def test_unknown_input_rejected():
    with pytest.raises(ValueError, match="unknown input"):
        _gb().add_inputs("in").add_layer(
            "a", DenseLayer(n_out=4), "nonexistent"
        )


def test_cycle_impossible_by_construction():
    # vertices may only reference already-added names, so cycles can't be
    # expressed through the builder — the config-level check still guards
    # hand-built configs
    conf = ComputationGraphConfiguration(
        inputs=["in"],
        outputs=["a"],
        vertices={"a": None, "b": None},
        vertex_inputs={"a": ["b"], "b": ["a"]},
    )
    with pytest.raises(ValueError, match="unreachable or cyclic"):
        conf.topological_order()


def test_serde_round_trip():
    conf = (
        _gb()
        .add_inputs("in")
        .add_layer("a", DenseLayer(n_out=4, activation="tanh"), "in")
        .add_vertex("s", ScaleVertex(scale=0.5), "a")
        .add_layer("out", OutputLayer(n_out=2, activation="softmax"), "s")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(6))
        .build()
    )
    conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
    assert conf2.vertex_inputs == conf.vertex_inputs
    assert conf2.vertices["s"].scale == 0.5
    assert conf2.vertices["out"].layer.n_in == 4
    # the rebuilt conf drives an identical network
    net1 = ComputationGraph(conf).init()
    net2 = ComputationGraph(conf2).init()
    x, _ = _xy(4, 6, 2)
    np.testing.assert_allclose(
        np.asarray(net1.output(x)), np.asarray(net2.output(x)), rtol=1e-6
    )


# -- vertex semantics --------------------------------------------------------

def test_vertex_forwards():
    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(2, 6))
    y = jnp.asarray(np.ones((2, 6), np.float32))
    env = {}
    assert MergeVertex().forward([x, y], env).shape == (2, 12)
    np.testing.assert_allclose(
        ElementWiseVertex(op="add").forward([x, y], env), np.asarray(x) + 1
    )
    np.testing.assert_allclose(
        ElementWiseVertex(op="subtract").forward([x, y], env), np.asarray(x) - 1
    )
    np.testing.assert_allclose(
        ElementWiseVertex(op="product").forward([x, y], env), np.asarray(x)
    )
    np.testing.assert_allclose(
        ElementWiseVertex(op="average").forward([x, y], env),
        (np.asarray(x) + 1) / 2,
    )
    np.testing.assert_allclose(
        ElementWiseVertex(op="max").forward([x, y], env),
        np.maximum(np.asarray(x), 1),
    )
    np.testing.assert_allclose(
        SubsetVertex(from_=1, to=3).forward([x], env), np.asarray(x)[:, 1:4]
    )
    st = StackVertex().forward([x, y], env)
    assert st.shape == (4, 6)
    np.testing.assert_allclose(
        UnstackVertex(from_=1, stack_size=2).forward([st], env), np.asarray(y)
    )
    np.testing.assert_allclose(
        ScaleVertex(scale=2.0).forward([x], env), 2 * np.asarray(x)
    )
    np.testing.assert_allclose(
        ShiftVertex(shift=1.5).forward([x], env), np.asarray(x) + 1.5
    )
    assert ReshapeVertex(new_shape=(2, 3)).forward([x], env).shape == (2, 2, 3)
    d = L2Vertex().forward([x, y], env)
    assert d.shape == (2, 1)
    expected = np.sqrt(np.sum((np.asarray(x) - 1) ** 2, axis=1) + 1e-8)
    np.testing.assert_allclose(np.asarray(d)[:, 0], expected, rtol=1e-5)
    nz = L2NormalizeVertex().forward([x], env)
    norms = np.linalg.norm(np.asarray(nz), axis=1)
    np.testing.assert_allclose(norms, 1.0, rtol=1e-4)


def test_rnn_vertices():
    xt = jnp.asarray(np.random.default_rng(0).standard_normal((2, 5, 3)).astype(np.float32))
    xf = jnp.asarray(np.ones((2, 3), np.float32))
    env = {"activations": {"seq": xt}, "input_masks": {}}
    last = LastTimeStepVertex().forward([xt], env)
    np.testing.assert_allclose(last, np.asarray(xt)[:, -1])
    # masked: example 0 has 3 valid steps, example 1 has 5
    mask = jnp.asarray(np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float32))
    env_m = {"activations": {"seq": xt}, "input_masks": {"in": mask}}
    last_m = LastTimeStepVertex(mask_input="in").forward([xt], env_m)
    np.testing.assert_allclose(last_m[0], np.asarray(xt)[0, 2])
    np.testing.assert_allclose(last_m[1], np.asarray(xt)[1, 4])
    dup = DuplicateToTimeSeriesVertex(ref_input="seq").forward([xf], env)
    assert dup.shape == (2, 5, 3)
    np.testing.assert_allclose(dup[:, 2], np.asarray(xf))


# -- training ----------------------------------------------------------------

def test_fanout_gradient_accumulation():
    """A vertex consumed by two branches must receive the SUM of both
    branch gradients (reference: ComputationGraph.java:1480-1502 epsilon
    accumulation) — checked against finite differences."""
    conf = (
        _gb()
        .add_inputs("in")
        .add_layer("shared", DenseLayer(n_out=5, activation="tanh"), "in")
        .add_layer("b1", DenseLayer(n_out=5, activation="sigmoid"), "shared")
        .add_layer("b2", DenseLayer(n_out=5, activation="tanh"), "shared")
        .add_vertex("add", ElementWiseVertex(op="add"), "b1", "b2")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax"), "add")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(4))
        .build()
    )
    net = ComputationGraph(conf).init()
    x, y = _xy(8, 4, 3)
    assert check_gradients_graph(net, [x], [y], max_checks=60)


def test_multi_input_multi_output_training():
    conf = (
        _gb(updater="adam", lr=0.01)
        .add_inputs("inA", "inB")
        .add_layer("dA", DenseLayer(n_out=8, activation="relu"), "inA")
        .add_layer("dB", DenseLayer(n_out=8, activation="relu"), "inB")
        .add_vertex("m", MergeVertex(), "dA", "dB")
        .add_layer("trunk", DenseLayer(n_out=8, activation="tanh"), "m")
        .add_layer("out1", OutputLayer(n_out=3, activation="softmax"), "trunk")
        .add_layer("out2", OutputLayer(n_out=2, activation="softmax"), "trunk")
        .set_outputs("out1", "out2")
        .set_input_types(InputType.feed_forward(6), InputType.feed_forward(4))
        .build()
    )
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(3)
    xa = rng.standard_normal((32, 6)).astype(np.float32)
    xb = rng.standard_normal((32, 4)).astype(np.float32)
    y1 = np.zeros((32, 3), np.float32)
    y1[np.arange(32), rng.integers(0, 3, 32)] = 1.0
    y2 = np.zeros((32, 2), np.float32)
    y2[np.arange(32), rng.integers(0, 2, 32)] = 1.0
    mds = MultiDataSet([xa, xb], [y1, y2])
    s0 = net.score(mds)
    net.fit(mds, epochs=40, batch_size=32, async_prefetch=False)
    s1 = net.score(mds)
    assert s1 < s0 * 0.5
    o1, o2 = net.output(xa, xb)
    assert o1.shape == (32, 3) and o2.shape == (32, 2)


def test_seq2vec_with_rnn_vertices():
    """LSTM encoder -> LastTimeStep -> classifier, with masking — the
    reference's rnn-vertex pattern (LastTimeStepVertex.java)."""
    conf = (
        _gb(updater="adam", lr=0.02)
        .add_inputs("seq")
        .add_layer("lstm", LSTM(n_out=8, activation="tanh"), "seq")
        .add_vertex("last", LastTimeStepVertex(mask_input="seq"), "lstm")
        .add_layer("out", OutputLayer(n_out=2, activation="softmax"), "last")
        .set_outputs("out")
        .set_input_types(InputType.recurrent(4))
        .build()
    )
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(5)
    x = rng.standard_normal((16, 6, 4)).astype(np.float32)
    y = np.zeros((16, 2), np.float32)
    y[np.arange(16), rng.integers(0, 2, 16)] = 1.0
    mask = np.ones((16, 6), np.float32)
    mask[:8, 4:] = 0.0
    mds = MultiDataSet([x], [y], [mask], None)
    s0 = net.score(mds)
    net.fit(mds, epochs=30, batch_size=16, async_prefetch=False)
    assert net.score(mds) < s0


def test_gradcheck_merge_subset_scale():
    conf = (
        _gb()
        .add_inputs("in")
        .add_layer("a", DenseLayer(n_out=4, activation="tanh"), "in")
        .add_layer("b", DenseLayer(n_out=6, activation="sigmoid"), "in")
        .add_vertex("m", MergeVertex(), "a", "b")
        .add_vertex("sub", SubsetVertex(from_=2, to=7), "m")
        .add_vertex("sc", ScaleVertex(scale=1.5), "sub")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax"), "sc")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(5))
        .build()
    )
    net = ComputationGraph(conf).init()
    x, y = _xy(6, 5, 3, seed=2)
    assert check_gradients_graph(net, [x], [y], max_checks=60)


def test_l2_vertices_gradcheck():
    conf = (
        _gb()
        .add_inputs("a", "b")
        .add_layer("ea", DenseLayer(n_out=6, activation="tanh"), "a")
        .add_layer("eb", DenseLayer(n_out=6, activation="tanh"), "b")
        .add_vertex("na", L2NormalizeVertex(), "ea")
        .add_vertex("nb", L2NormalizeVertex(), "eb")
        .add_vertex("dist", L2Vertex(), "na", "nb")
        .add_layer("out", OutputLayer(n_out=2, activation="softmax"), "dist")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(4), InputType.feed_forward(4))
        .build()
    )
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(4)
    xa = rng.standard_normal((6, 4)).astype(np.float32)
    xb = rng.standard_normal((6, 4)).astype(np.float32)
    y = np.zeros((6, 2), np.float32)
    y[np.arange(6), rng.integers(0, 2, 6)] = 1.0
    assert check_gradients_graph(net, [xa, xb], [y], max_checks=50)


def test_evaluate_single_output():
    conf = (
        _gb(updater="adam", lr=0.05)
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_out=16, activation="relu"), "in")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax"), "d")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(8))
        .build()
    )
    net = ComputationGraph(conf).init()
    x, y = _xy(64, 8, 3)
    net.fit(x, y, epochs=60, batch_size=32, async_prefetch=False)
    ev = net.evaluate(x, y)
    assert ev.accuracy() > 0.8


def test_auto_merge_on_multi_input_layer():
    """add_layer with >1 input auto-inserts a MergeVertex (reference:
    ComputationGraphConfiguration.java:580-584) — ADVICE r2 medium."""
    conf = (
        _gb()
        .add_inputs("in")
        .add_layer("a", DenseLayer(n_out=5, activation="tanh"), "in")
        .add_layer("b", DenseLayer(n_out=7, activation="tanh"), "in")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax"), "a", "b")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(4))
        .build()
    )
    assert "out-merge" in conf.vertices
    assert isinstance(conf.vertices["out-merge"], MergeVertex)
    assert conf.vertex_inputs["out"] == ["out-merge"]
    # the output layer sees the concatenated width (5 + 7 = 12)
    assert conf.vertices["out"].layer.n_in == 12
    net = ComputationGraph(conf).init()
    x, y = _xy(8, 4, 3)
    out = net.output(x)
    assert out.shape == (8, 3)
    net.fit(x, y, epochs=2, batch_size=8, async_prefetch=False)


def test_output_with_input_masks():
    """output(input_masks=...) threads masks to LastTimeStepVertex so
    inference matches training on variable-length sequences (ADVICE r2)."""
    conf = (
        _gb()
        .add_inputs("in")
        .add_layer("lstm", LSTM(n_out=6, activation="tanh"), "in")
        .add_vertex("last", LastTimeStepVertex(mask_input="in"), "lstm")
        .add_layer("out", OutputLayer(n_out=2, activation="softmax"), "last")
        .set_outputs("out")
        .set_input_types(InputType.recurrent(3))
        .build()
    )
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 5, 3)).astype(np.float32)
    mask = np.ones((4, 5), np.float32)
    mask[0, 3:] = 0.0  # example 0 has length 3
    out_masked = np.asarray(net.output(x, input_masks=[mask]))
    out_plain = np.asarray(net.output(x))
    # example 0 must use step 2's state, not the padded last step
    x_trunc = x.copy()
    x_trunc[0, 3:] = 123.0  # garbage past the mask must not matter
    out_masked2 = np.asarray(net.output(x_trunc, input_masks=[mask]))
    np.testing.assert_allclose(out_masked[0], out_masked2[0], atol=2e-4)
    assert not np.allclose(out_masked[0], out_plain[0])


def test_clone_carries_updater_and_counters():
    conf = (
        _gb(updater="adam", lr=0.05)
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax"), "d")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(8))
        .build()
    )
    net = ComputationGraph(conf).init()
    x, y = _xy(16, 8, 3)
    net.fit(x, y, epochs=3, batch_size=16, async_prefetch=False)
    other = net.clone()
    assert other.iteration == net.iteration
    assert other.epoch == net.epoch
    a = np.concatenate([np.ravel(l) for l in
                        __import__("jax").tree_util.tree_leaves(net.upd_state)])
    b = np.concatenate([np.ravel(l) for l in
                        __import__("jax").tree_util.tree_leaves(other.upd_state)])
    np.testing.assert_array_equal(a, b)
    # continued training must be bit-identical between original and clone
    net.fit(x, y, epochs=1, batch_size=16, async_prefetch=False)
    other.fit(x, y, epochs=1, batch_size=16, async_prefetch=False)
    np.testing.assert_allclose(
        np.asarray(net.params()), np.asarray(other.params()), atol=0
    )


# -- round-3 parity: TBPTT / rnnTimeStep / CenterLoss / transfer -------------


def _chain_rnn_mln_and_cg(seed=21, tbptt=True, fwd=4, bwd=None):
    """The same LSTM chain as an MLN and as a CG (identical seeds =>
    identical init, since both fold_in layer index 0,1)."""
    from deeplearning4j_tpu.nn.conf import BackpropType
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def base():
        return (
            NeuralNetConfiguration.builder()
            .seed(seed)
            .updater("sgd")
            .learning_rate(0.1)
            .weight_init("xavier")
        )

    lb = (
        base().list()
        .layer(LSTM(n_out=6, activation="tanh"))
        .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(3))
    )
    gb = (
        base().graph_builder()
        .add_inputs("in")
        .add_layer("lstm", LSTM(n_out=6, activation="tanh"), "in")
        .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax",
                                         loss="mcxent"), "lstm")
        .set_outputs("out")
        .set_input_types(InputType.recurrent(3))
    )
    if tbptt:
        lb = lb.backprop_type(BackpropType.TRUNCATED_BPTT).t_bptt_lengths(fwd, bwd)
        gb = gb.backprop_type("tbptt").t_bptt_lengths(fwd, bwd)
    return MultiLayerNetwork(lb.build()).init(), ComputationGraph(gb.build()).init()


def _rnn_xy(n=8, t=12, nin=3, nout=2, seed=5):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, t, nin)).astype(np.float32)
    y = np.zeros((n, t, nout), np.float32)
    idx = rng.integers(0, nout, (n, t))
    for i in range(n):
        y[i, np.arange(t), idx[i]] = 1.0
    return x, y


def test_cg_tbptt_matches_mln():
    """CG TBPTT segment loop == MLN TBPTT on the same chain (reference:
    ComputationGraph.doTruncatedBPTT mirrors the MLN path)."""
    mln, cg = _chain_rnn_mln_and_cg(tbptt=True, fwd=4)
    np.testing.assert_allclose(np.asarray(mln.params()),
                               np.asarray(cg.params()), atol=0)
    x, y = _rnn_xy()
    mln.fit(x, y, epochs=2, batch_size=8, async_prefetch=False)
    cg.fit(x, y, epochs=2, batch_size=8, async_prefetch=False)
    assert mln.iteration == cg.iteration  # same number of segment steps
    np.testing.assert_allclose(np.asarray(mln.params()),
                               np.asarray(cg.params()), rtol=2e-5, atol=2e-6)


def test_cg_tbptt_bwd_truncation_matches_mln():
    mln, cg = _chain_rnn_mln_and_cg(tbptt=True, fwd=6, bwd=3)
    x, y = _rnn_xy(t=12)
    mln.fit(x, y, epochs=1, batch_size=8, async_prefetch=False)
    cg.fit(x, y, epochs=1, batch_size=8, async_prefetch=False)
    np.testing.assert_allclose(np.asarray(mln.params()),
                               np.asarray(cg.params()), rtol=2e-5, atol=2e-6)


def test_cg_rnn_time_step_streaming_equivalence():
    """Streaming chunks through rnn_time_step == one full-sequence output
    (reference: ComputationGraph.rnnTimeStep)."""
    _, cg = _chain_rnn_mln_and_cg(tbptt=False)
    x, _ = _rnn_xy(n=4, t=10)
    full = np.asarray(cg.output(x))
    cg.rnn_clear_previous_state()
    c1 = np.asarray(cg.rnn_time_step(x[:, :4]))
    c2 = np.asarray(cg.rnn_time_step(x[:, 4:7]))
    c3 = np.asarray(cg.rnn_time_step(x[:, 7:]))
    streamed = np.concatenate([c1, c2, c3], axis=1)
    np.testing.assert_allclose(streamed, full, rtol=2e-5, atol=2e-6)
    # single-step [b, nin] form
    cg.rnn_clear_previous_state()
    s = np.asarray(cg.rnn_time_step(x[:, 0]))
    np.testing.assert_allclose(s, full[:, 0], rtol=2e-5, atol=2e-6)


def test_cg_center_loss_head():
    """CenterLossOutputLayer as a CG head: trains, centers move (reference:
    CenterLossOutputLayer.java wired through the graph path)."""
    from deeplearning4j_tpu.nn.conf import CenterLossOutputLayer

    conf = (
        _gb(updater="adam", lr=0.05)
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
        .add_layer("out", CenterLossOutputLayer(
            n_out=3, activation="softmax", loss="mcxent",
            lambda_=0.1, alpha=0.3), "d")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(6))
        .build()
    )
    net = ComputationGraph(conf).init()
    x, y = _xy(48, 6, 3)
    pidx = net._pidx["out"]
    centers0 = np.asarray(net.state_list[pidx]["centers"])
    net.fit(x, y, epochs=40, batch_size=48, async_prefetch=False)
    centers1 = np.asarray(net.state_list[pidx]["centers"])
    assert not np.allclose(centers0, centers1)  # EMA updates happened
    assert net.evaluate(x, y).accuracy() > 0.8
    # the center term shapes the features: same run with lambda_=0 must
    # leave larger within-class scatter (relative to feature scale) than
    # the center-pulled run
    def within_scatter(trained):
        feats = np.asarray(trained.feed_forward(x)["d"])
        labels = y.argmax(1)
        scale = np.linalg.norm(feats - feats.mean(0), axis=1).mean() + 1e-12
        return np.mean([
            np.linalg.norm(
                feats[labels == k] - feats[labels == k].mean(0), axis=1
            ).mean()
            for k in range(3)
        ]) / scale

    conf0 = (
        _gb(updater="adam", lr=0.05)
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
        .add_layer("out", CenterLossOutputLayer(
            n_out=3, activation="softmax", loss="mcxent",
            lambda_=0.0, alpha=0.3), "d")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(6))
        .build()
    )
    net0 = ComputationGraph(conf0).init()
    net0.fit(x, y, epochs=40, batch_size=48, async_prefetch=False)
    assert within_scatter(net) < within_scatter(net0)


def test_cg_transfer_learning():
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.nn.transferlearning import TransferLearning

    conf = (
        _gb(updater="sgd", lr=0.1)
        .add_inputs("in")
        .add_layer("f1", DenseLayer(n_out=10, activation="relu"), "in")
        .add_layer("f2", DenseLayer(n_out=8, activation="relu"), "f1")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax"), "f2")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(6))
        .build()
    )
    src = ComputationGraph(conf).init()
    x, y = _xy(32, 6, 3)
    src.fit(x, y, epochs=3, batch_size=32, async_prefetch=False)

    # freeze the feature front, swap the head for a 4-class one
    new = (
        TransferLearning.GraphBuilder(src)
        .set_feature_extractor("f2")
        .remove_vertex_and_connections("out")
        .add_layer("newout", L.OutputLayer(n_in=8, n_out=4,
                                           activation="softmax"), "f2")
        .set_outputs("newout")
        .build()
    )
    # surviving params are shared/copied
    np.testing.assert_array_equal(
        np.asarray(new.params_list[new._pidx["f1"]]["W"]),
        np.asarray(src.params_list[src._pidx["f1"]]["W"]),
    )
    # frozen front must not move during fit
    w_before = np.asarray(new.params_list[new._pidx["f1"]]["W"]).copy()
    y4 = np.zeros((32, 4), np.float32)
    y4[np.arange(32), np.random.default_rng(1).integers(0, 4, 32)] = 1.0
    new.fit(x, y4, epochs=3, batch_size=32, async_prefetch=False)
    np.testing.assert_array_equal(
        np.asarray(new.params_list[new._pidx["f1"]]["W"]), w_before
    )
    assert new.output(x).shape == (32, 4)


# -- scan-over-identical-blocks (PR 16) ---------------------------------------
# Runs of identically-configured residual blocks compile as ONE scanned
# body over stacked params instead of N unrolled copies. The contract:
# outputs and training trajectories are BIT-identical to the unrolled
# walk (jax.lax.scan over stacked slots traces the same per-unit body;
# fold_in on a traced row index equals the concrete fold_in), and
# compile_total{kind="graph_block"} drops from one count per block to
# one per run.


def _scan_resnet(block_scan):
    from deeplearning4j_tpu.models.resnet import resnet_conf

    conf = resnet_conf(blocks=(3, 3), widths=(2, 4), num_classes=3,
                       image_size=8, stem_width=4)
    net = ComputationGraph(conf).init()
    net.set_block_scan(block_scan)
    return net


def _scan_xy(n=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 8, 8, 3)).astype(np.float32)
    y = np.zeros((n, 3), np.float32)
    y[np.arange(n), rng.integers(0, 3, n)] = 1.0
    return x, y


def test_block_scan_detects_identity_runs():
    """blocks=(3,3) has two runs of 2 identity blocks each (the stage
    entry block projects, so it can't join); blocks=(1,1) has none."""
    from deeplearning4j_tpu.models.resnet import resnet_conf, tiny_resnet_conf

    net = _scan_resnet(True)
    runs = net._block_runs()
    assert len(runs) == 2
    assert all(r["count"] == 2 for r in runs)
    tiny = ComputationGraph(tiny_resnet_conf()).init()
    assert tiny._block_runs() == []


def test_block_scan_output_and_training_bit_identical():
    """Scanned forward == unrolled forward bit for bit, eager and jitted,
    and a 3-step training run lands on byte-identical params."""
    x, y = _scan_xy()
    a, b = _scan_resnet("unroll"), _scan_resnet(True)
    np.testing.assert_array_equal(np.asarray(a.output(x)),
                                  np.asarray(b.output(x)))
    a.fit(x, y, epochs=3, batch_size=8, async_prefetch=False)
    b.fit(x, y, epochs=3, batch_size=8, async_prefetch=False)
    for p1, p2 in zip(a.params_list, b.params_list):
        for k in p1:
            np.testing.assert_array_equal(np.asarray(p1[k]),
                                          np.asarray(p2[k]))


def test_block_scan_collapses_graph_block_compile_counter():
    """compile_total{kind="graph_block"} counts traced block bodies:
    4 for the unrolled walk (2 runs x 2 blocks), 2 when scanned (one
    per run) — the collapse the bench artifact records."""
    from deeplearning4j_tpu.utils.metrics import get_registry

    gb = get_registry().counter(
        "compile_total", "jit cache insertions (fresh traces)",
        ("kind",)).labels("graph_block")
    x, y = _scan_xy()

    c0 = gb.value
    _scan_resnet("unroll").fit(x, y, epochs=1, batch_size=8,
                               async_prefetch=False)
    unrolled = gb.value - c0
    c0 = gb.value
    _scan_resnet(True).fit(x, y, epochs=1, batch_size=8,
                           async_prefetch=False)
    scanned = gb.value - c0
    assert (unrolled, scanned) == (4, 2)
