"""Runtime lock-order sanitizer — lockdep for the framework's threads.

The stack is a dozen cooperating thread pools (serving collector and
dispatcher, decode engine, sparse prefetch, ledger/watchdog daemons,
paramserver drains), and every deadlock class it has hit so far —
reversed acquisition orders, blocking I/O under a mutex, a device sync
while holding the admission lock — is *observable* at runtime long
before two threads actually wedge. This module is the observer:

- Opt-in via ``DL4J_LOCKCHECK=1`` (or ``install()``). When armed it
  wraps ``threading.Lock`` / ``RLock`` / ``Condition`` *construction*
  for callers inside ``deeplearning4j_tpu/`` only — stdlib, jax and
  third-party locks stay raw — and keeps, per thread, the ordered set
  of traced locks currently held.
- Every blocking acquisition attempted while other traced locks are
  held records a directed edge ``held -> wanted`` in a process-global
  lock-order graph, with a bounded repo-frames-only witness stack
  captured the first time each edge appears. Two code paths that take
  the same two locks in opposite orders produce a cycle — a potential
  deadlock that fires as a CN001 finding (analysis/concurrency_audit)
  even when the timing never actually wedges.
- Blocking calls made while holding a traced lock — ``time.sleep``,
  ``queue.Queue.get/put``, ``Condition``/``Event`` waits on *another*
  lock's condition, ``Thread.join``, ``socket.create_connection``,
  ``jax.block_until_ready`` — are recorded as CN002 evidence, and a
  jitted dispatch entered with a lock held (cooperative
  ``note_dispatch()`` hooks in the fit loop and the decode engine) as
  CN003.
- Deadlock forensics: lock ownership plus a waiter wait-graph
  (``forensics()``) that names *who holds what and who waits on whom*;
  utils/blackbox embeds it in every dump so a watchdog-caught hang
  renders as a named cycle, not a stack soup.

Off-path contract (the devprof/runledger bar): when the sanitizer is
not installed nothing in the process is patched, and every cooperative
hook (``note_dispatch``/``note_blocking``) is ONE module-global read —
pinned <10us by tests. Traced locks created while armed keep working
after ``uninstall()`` by delegating on the same one-global-read check.

Identity: locks are keyed by their *construction site* (``path:line``,
lockdep's "lock class"), not by instance — a pool that builds one lock
per replica still converges to one node per site, which is what keeps
the graph bounded and lets cross-instance order violations connect.
"""

from __future__ import annotations

import _thread
import os
import queue
import socket
import sys
import threading
import time
from typing import Dict, List, Optional

_SELF_FILE = os.path.abspath(__file__)
_PKG_DIR = os.path.dirname(os.path.dirname(_SELF_FILE))
_REPO_ROOT = os.path.dirname(_PKG_DIR)

# originals captured once at import — install() swaps them out, traced
# paths and uninstall() always go through this table
_ORIG = {
    "Lock": threading.Lock,
    "RLock": threading.RLock,
    "Condition": threading.Condition,
    "sleep": time.sleep,
    "queue_get": queue.Queue.get,
    "queue_put": queue.Queue.put,
    "cond_wait": threading.Condition.wait,
    "event_wait": threading.Event.wait,
    "thread_join": threading.Thread.join,
    "create_connection": socket.create_connection,
}

_WITNESS_FRAMES = 8


class _State:
    """All sanitizer state. One instance per install(); dropped whole on
    uninstall() so a stale thread finishing a traced acquire cannot
    corrupt the next session's graph."""

    def __init__(self):
        # a RAW lock (never traced): the sanitizer must not feed itself
        self.mu = _thread.allocate_lock()
        self.tls = threading.local()
        # site -> {"name", "kind", "created"}
        self.locks: Dict[str, dict] = {}
        # (held_site, wanted_site) -> {"count", "thread", "witness"}
        self.edges: Dict[tuple, dict] = {}
        # (kind, site) -> {"count", "held", "thread", "witness", "func"}
        self.blocking: Dict[tuple, dict] = {}
        # (what, site) -> same shape as blocking
        self.dispatch: Dict[tuple, dict] = {}
        # id(traced lock) -> {"site", "thread", "ident", "depth"}
        self.owners: Dict[int, dict] = {}
        # thread ident -> {"thread", "site", "lock", "since"}
        self.waiting: Dict[int, dict] = {}

    def held(self) -> list:
        h = getattr(self.tls, "held", None)
        if h is None:
            h = self.tls.held = []
        return h


_STATE: Optional[_State] = None


# -- frame helpers ------------------------------------------------------------

def _witness(skip: int = 2) -> List[str]:
    """Repo-frames-only stack (innermost first), bounded — enough to
    *name* where an edge was minted without dragging pytest/threading
    frames along."""
    out: List[str] = []
    try:
        f = sys._getframe(skip)
    except ValueError:
        return out
    depth = 0
    while f is not None and depth < 50 and len(out) < _WITNESS_FRAMES:
        fn = f.f_code.co_filename
        if fn.startswith(_REPO_ROOT) and fn != _SELF_FILE:
            rel = os.path.relpath(fn, _REPO_ROOT).replace(os.sep, "/")
            out.append(f"{rel}:{f.f_lineno} in {f.f_code.co_name}")
        f = f.f_back
        depth += 1
    return out


def _nearest_repo_site(skip: int = 2):
    """(``rel:line``, function) of the innermost repo frame, or None."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return None
    depth = 0
    while f is not None and depth < 50:
        fn = f.f_code.co_filename
        if fn.startswith(_REPO_ROOT) and fn != _SELF_FILE:
            rel = os.path.relpath(fn, _REPO_ROOT).replace(os.sep, "/")
            return f"{rel}:{f.f_lineno}", f.f_code.co_name
        f = f.f_back
        depth += 1
    return None


def _construction_site(depth: int):
    """Caller-frame filter for the patched constructors: only wrap a
    lock whose *immediate* constructing frame is framework code — queue
    internals, threading.Event, jax and user code keep raw primitives."""
    try:
        f = sys._getframe(depth)
    except ValueError:
        return None
    fn = f.f_code.co_filename
    if not fn.startswith(_PKG_DIR) or fn == _SELF_FILE:
        return None
    rel = os.path.relpath(fn, _REPO_ROOT).replace(os.sep, "/")
    return f"{rel}:{f.f_lineno}", f.f_code.co_name


# -- traced lock wrappers -----------------------------------------------------

def _register_site(st: _State, site: str, kind: str, name: Optional[str]):
    with st.mu:
        rec = st.locks.get(site)
        if rec is None:
            st.locks[site] = {"name": name, "kind": kind, "created": 1}
        else:
            rec["created"] += 1
            if name and not rec.get("name"):
                rec["name"] = name


def _record_edges(st: _State, held: list, site: str):
    """Directed order edges held -> site, minted at acquire ATTEMPT so
    a pair of threads that really do deadlock still leaves both edges
    (and both witnesses) in the graph."""
    tname = threading.current_thread().name
    with st.mu:
        for _lid, hsite, _d in held:
            if hsite == site:
                continue
            rec = st.edges.get((hsite, site))
            if rec is None:
                st.edges[(hsite, site)] = {
                    "count": 1, "thread": tname, "witness": _witness(3)}
            else:
                rec["count"] += 1


def _acquire_traced(lock, blocking, timeout):
    st = _STATE
    inner = lock._inner
    if st is None:
        return inner.acquire(blocking, timeout)
    held = st.held()
    lid = id(lock)
    if lock._reentrant:
        for ent in held:
            if ent[0] == lid:
                got = inner.acquire(blocking, timeout)
                if got:
                    ent[2] += 1
                    with st.mu:
                        own = st.owners.get(lid)
                        if own is not None:
                            own["depth"] = ent[2]
                return got
    ident = threading.get_ident()
    tname = threading.current_thread().name
    if blocking:
        if held:
            _record_edges(st, held, lock._site)
        with st.mu:
            st.waiting[ident] = {"thread": tname, "site": lock._site,
                                 "lock": lid, "since": time.monotonic()}
        try:
            got = inner.acquire(blocking, timeout)
        finally:
            with st.mu:
                st.waiting.pop(ident, None)
    else:
        # trylocks cannot participate in a deadlock — no order edge
        got = inner.acquire(False)
    if got:
        held.append([lid, lock._site, 1])
        with st.mu:
            st.owners[lid] = {"site": lock._site, "thread": tname,
                              "ident": ident, "depth": 1}
    return got


def _release_traced(lock):
    st = _STATE
    lock._inner.release()
    if st is None:
        return
    lid = id(lock)
    held = st.held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == lid:
            held[i][2] -= 1
            if held[i][2] <= 0:
                del held[i]
                with st.mu:
                    st.owners.pop(lid, None)
            else:
                with st.mu:
                    own = st.owners.get(lid)
                    if own is not None:
                        own["depth"] = held[i][2]
            return
    # released by a thread that never recorded the acquire (pre-install
    # hold, or a plain Lock handed across threads): just drop ownership
    with st.mu:
        st.owners.pop(lid, None)


class _TracedLock:
    """threading.Lock with acquisition-order accounting."""

    _reentrant = False

    def __init__(self, site: str, label: str, name: Optional[str] = None):
        self._inner = _ORIG["Lock"]()
        self._site = site
        self._label = label
        st = _STATE
        if st is not None:
            _register_site(st, site, "Lock", name)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        return _acquire_traced(self, blocking, timeout)

    def release(self):
        _release_traced(self)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<TracedLock {self._site} ({self._label})>"


class _TracedRLock(_TracedLock):
    """threading.RLock with accounting; implements the Condition
    protocol (_release_save/_acquire_restore/_is_owned) so
    ``threading.Condition(traced_rlock)`` waits correctly AND keeps the
    held-set honest across the wait (the lock is NOT held while the
    waiter sleeps)."""

    _reentrant = True

    def __init__(self, site: str, label: str, name: Optional[str] = None):
        self._inner = _ORIG["RLock"]()
        self._site = site
        self._label = label
        st = _STATE
        if st is not None:
            _register_site(st, site, "RLock", name)

    def locked(self):
        locked = getattr(self._inner, "locked", None)
        return locked() if locked is not None else self._inner._is_owned()

    def _drop_bookkeeping(self):
        st = _STATE
        if st is None:
            return None
        lid = id(self)
        held = st.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == lid:
                depth = held[i][2]
                del held[i]
                with st.mu:
                    st.owners.pop(lid, None)
                return depth
        return None

    def _restore_bookkeeping(self, depth):
        st = _STATE
        if st is None or depth is None:
            return
        lid = id(self)
        st.held().append([lid, self._site, depth])
        with st.mu:
            st.owners[lid] = {
                "site": self._site,
                "thread": threading.current_thread().name,
                "ident": threading.get_ident(), "depth": depth}

    def _release_save(self):
        depth = self._drop_bookkeeping()
        return self._inner._release_save(), depth

    def _acquire_restore(self, saved):
        inner_state, depth = saved
        st = _STATE
        ident = threading.get_ident()
        if st is not None:
            with st.mu:
                st.waiting[ident] = {
                    "thread": threading.current_thread().name,
                    "site": self._site, "lock": id(self),
                    "since": time.monotonic()}
        try:
            self._inner._acquire_restore(inner_state)
        finally:
            if st is not None:
                with st.mu:
                    st.waiting.pop(ident, None)
        self._restore_bookkeeping(depth)

    def _is_owned(self):
        return self._inner._is_owned()

    def __repr__(self):
        return f"<TracedRLock {self._site} ({self._label})>"


# -- patched constructors -----------------------------------------------------

def _lock_factory():
    st = _STATE
    if st is None:
        return _ORIG["Lock"]()
    site = _construction_site(2)
    if site is None:
        return _ORIG["Lock"]()
    return _TracedLock(site[0], site[1])


def _rlock_factory():
    st = _STATE
    if st is None:
        return _ORIG["RLock"]()
    site = _construction_site(2)
    if site is None:
        return _ORIG["RLock"]()
    return _TracedRLock(site[0], site[1])


def _condition_factory(lock=None):
    st = _STATE
    if st is not None and lock is None:
        site = _construction_site(2)
        if site is not None:
            lock = _TracedRLock(site[0], site[1])
    return _ORIG["Condition"](lock)


# -- blocking-under-lock probes ----------------------------------------------

def _note_blocking_impl(st: _State, kind: str, exempt_id: Optional[int],
                        skip: int):
    held = getattr(st.tls, "held", None)
    if not held:
        return
    held_sites = [h[1] for h in held if h[0] != exempt_id]
    if not held_sites:
        return
    if getattr(st.tls, "in_probe", False):
        return
    st.tls.in_probe = True
    try:
        near = _nearest_repo_site(skip + 1)
        site, func = near if near is not None else ("<external>", "?")
        tname = threading.current_thread().name
        with st.mu:
            rec = st.blocking.get((kind, site))
            if rec is None:
                st.blocking[(kind, site)] = {
                    "count": 1, "held": sorted(set(held_sites)),
                    "thread": tname, "func": func, "witness": _witness(skip + 1)}
            else:
                rec["count"] += 1
                for s in held_sites:
                    if s not in rec["held"]:
                        rec["held"].append(s)
    finally:
        st.tls.in_probe = False


def note_blocking(kind: str) -> None:
    """Cooperative CN002 hook for blocking operations the patch set
    cannot see (custom socket loops, subprocess waits). Off = one
    module-global read."""
    st = _STATE
    if st is None:
        return
    _note_blocking_impl(st, kind, None, 2)


def note_dispatch(what: str) -> None:
    """Cooperative CN003 hook: call at a jitted-dispatch boundary (the
    fit step, the decode engine step). Records only when the calling
    thread holds a traced lock. Off = one module-global read."""
    st = _STATE
    if st is None:
        return
    held = getattr(st.tls, "held", None)
    if not held:
        return
    held_sites = [h[1] for h in held]
    near = _nearest_repo_site(2)
    site, func = near if near is not None else ("<external>", "?")
    tname = threading.current_thread().name
    with st.mu:
        rec = st.dispatch.get((what, site))
        if rec is None:
            st.dispatch[(what, site)] = {
                "count": 1, "held": sorted(set(held_sites)),
                "thread": tname, "func": func, "witness": _witness(2)}
        else:
            rec["count"] += 1


def _traced_sleep(secs):
    st = _STATE
    if st is not None:
        _note_blocking_impl(st, "time.sleep", None, 2)
    return _ORIG["sleep"](secs)


def _traced_queue_get(self, block=True, timeout=None):
    st = _STATE
    if st is not None and block:
        _note_blocking_impl(st, "queue.get", None, 2)
    return _ORIG["queue_get"](self, block, timeout)


def _traced_queue_put(self, item, block=True, timeout=None):
    st = _STATE
    if st is not None and block:
        _note_blocking_impl(st, "queue.put", None, 2)
    return _ORIG["queue_put"](self, item, block, timeout)


def _direct_caller_in_repo() -> bool:
    try:
        fn = sys._getframe(2).f_code.co_filename
    except ValueError:
        return False
    return fn.startswith(_REPO_ROOT) and fn != _SELF_FILE \
        and not fn.startswith(_REPO_ROOT + os.sep + ".")


def _traced_cond_wait(self, timeout=None):
    st = _STATE
    if st is not None and _direct_caller_in_repo():
        # waiting on the condition RELEASES its own lock — only the
        # *other* held locks make this a blocking-under-lock finding
        _note_blocking_impl(st, "condition.wait", id(self._lock), 2)
    return _ORIG["cond_wait"](self, timeout)


def _traced_event_wait(self, timeout=None):
    st = _STATE
    if st is not None and _direct_caller_in_repo():
        _note_blocking_impl(st, "event.wait", None, 2)
    return _ORIG["event_wait"](self, timeout)


def _traced_thread_join(self, timeout=None):
    st = _STATE
    if st is not None and _direct_caller_in_repo():
        _note_blocking_impl(st, "thread.join", None, 2)
    return _ORIG["thread_join"](self, timeout)


def _traced_create_connection(*args, **kwargs):
    st = _STATE
    if st is not None:
        _note_blocking_impl(st, "socket.connect", None, 2)
    return _ORIG["create_connection"](*args, **kwargs)


def _traced_block_until_ready(x):
    st = _STATE
    if st is not None:
        _note_blocking_impl(st, "device_sync", None, 2)
    return _ORIG["block_until_ready"](x)


# -- install / uninstall ------------------------------------------------------

def enabled() -> bool:
    return _STATE is not None


def install() -> None:
    """Arm the sanitizer: patch lock construction (framework callers
    only) and the blocking-call probe set. Idempotent."""
    global _STATE
    if _STATE is not None:
        return
    _STATE = _State()
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    time.sleep = _traced_sleep
    queue.Queue.get = _traced_queue_get
    queue.Queue.put = _traced_queue_put
    _ORIG["Condition"].wait = _traced_cond_wait
    threading.Event.wait = _traced_event_wait
    threading.Thread.join = _traced_thread_join
    socket.create_connection = _traced_create_connection
    try:
        import jax
        if "block_until_ready" not in _ORIG:
            _ORIG["block_until_ready"] = jax.block_until_ready
        jax.block_until_ready = _traced_block_until_ready
    except Exception:
        pass


def uninstall() -> None:
    """Restore every patched primitive and drop the state. Traced lock
    instances created while armed keep working (raw delegation)."""
    global _STATE
    if _STATE is None:
        return
    threading.Lock = _ORIG["Lock"]
    threading.RLock = _ORIG["RLock"]
    threading.Condition = _ORIG["Condition"]
    time.sleep = _ORIG["sleep"]
    queue.Queue.get = _ORIG["queue_get"]
    queue.Queue.put = _ORIG["queue_put"]
    _ORIG["Condition"].wait = _ORIG["cond_wait"]
    threading.Event.wait = _ORIG["event_wait"]
    threading.Thread.join = _ORIG["thread_join"]
    socket.create_connection = _ORIG["create_connection"]
    if "block_until_ready" in _ORIG:
        try:
            import jax
            jax.block_until_ready = _ORIG["block_until_ready"]
        except Exception:
            pass
    _STATE = None


def reset() -> None:
    """Clear the recorded graph but stay armed (fresh run boundary)."""
    st = _STATE
    if st is None:
        return
    with st.mu:
        st.edges.clear()
        st.blocking.clear()
        st.dispatch.clear()


def traced_lock(name: Optional[str] = None):
    """Explicitly-traced Lock for tests/fixtures outside the package
    tree (the constructor patch only auto-wraps framework callers).
    Requires install()."""
    if _STATE is None:
        raise RuntimeError("locktrace is not installed (DL4J_LOCKCHECK=1 "
                           "or locktrace.install())")
    near = _nearest_repo_site(2) or ("<external>:0", "?")
    site = name or near[0]
    return _TracedLock(site, near[1], name=name)


def traced_rlock(name: Optional[str] = None):
    """Explicitly-traced RLock (see traced_lock)."""
    if _STATE is None:
        raise RuntimeError("locktrace is not installed (DL4J_LOCKCHECK=1 "
                           "or locktrace.install())")
    near = _nearest_repo_site(2) or ("<external>:0", "?")
    site = name or near[0]
    return _TracedRLock(site, near[1], name=name)


# -- export ------------------------------------------------------------------

def snapshot() -> dict:
    """JSON-safe export of the whole runtime graph for
    analysis/concurrency_audit: lock classes, order edges with
    witnesses, blocking-under-lock records, dispatch-under-lock
    records."""
    st = _STATE
    if st is None:
        return {"enabled": False, "locks": {}, "edges": [],
                "blocking": [], "dispatch": []}
    with st.mu:
        locks = {site: dict(rec) for site, rec in st.locks.items()}
        edges = [{"src": a, "dst": b, **rec}
                 for (a, b), rec in st.edges.items()]
        blocking = [{"kind": k, "site": s, **rec}
                    for (k, s), rec in st.blocking.items()]
        dispatch = [{"what": w, "site": s, **rec}
                    for (w, s), rec in st.dispatch.items()]
    return {"enabled": True, "locks": locks, "edges": edges,
            "blocking": blocking, "dispatch": dispatch}


def _wait_cycles(st: _State) -> List[List[dict]]:
    """Thread-level wait-for cycles: A waits on a lock B owns, B waits
    on a lock A owns — the live deadlock, named. Called under st.mu."""
    cycles: List[List[dict]] = []
    seen_sigs = set()
    for start in list(st.waiting):
        path: List[dict] = []
        index: Dict[int, int] = {}
        cur = start
        while cur in st.waiting:
            if cur in index:
                cyc = path[index[cur]:]
                sig = frozenset(e["ident"] for e in cyc)
                if sig not in seen_sigs:
                    seen_sigs.add(sig)
                    cycles.append([{k: v for k, v in e.items()
                                    if k != "ident"} for e in cyc])
                break
            index[cur] = len(path)
            w = st.waiting[cur]
            own = st.owners.get(w["lock"])
            path.append({
                "ident": cur,
                "thread": w["thread"],
                "waits_for": w["site"],
                "waited_s": round(time.monotonic() - w["since"], 3),
                "held_by": own["thread"] if own else None,
            })
            if own is None:
                break
            cur = own["ident"]
    return cycles


def forensics() -> Optional[dict]:
    """Ownership + waiter wait-graph for crash/stall dumps (consumed by
    utils/blackbox). None when the sanitizer is off — the dump section
    simply doesn't exist then."""
    st = _STATE
    if st is None:
        return None
    with st.mu:
        held: Dict[str, List[dict]] = {}
        for own in st.owners.values():
            held.setdefault(own["thread"], []).append(
                {"site": own["site"], "depth": own["depth"]})
        waiting = [{"thread": w["thread"], "waits_for": w["site"],
                    "waited_s": round(time.monotonic() - w["since"], 3)}
                   for w in st.waiting.values()]
        cycles = _wait_cycles(st)
    return {"enabled": True, "held": held, "waiting": waiting,
            "deadlock_cycles": cycles}


if os.environ.get("DL4J_LOCKCHECK", "") == "1":
    install()
