"""Deterministic SIGTERM action chain.

Two subsystems want the TPU preemption signal: the checkpoint listener
(save the model before the VM disappears) and the flight recorder (dump
the black box). Each used to install its own `signal.signal` handler and
chain to whatever was there before — so INSTALLATION ORDER decided
whether the preemption save ran before the crash dump, and a listener
installed after the crash hooks silently demoted the dump to "whenever
the previous handler got around to it".

This module owns the one SIGTERM handler instead. Subsystems register
named actions with a priority; on SIGTERM every action runs in priority
order (checkpoint save = PRIORITY_SAVE, black-box dump = PRIORITY_DUMP,
so the save always precedes the dump regardless of who armed first),
then the pre-chain handler (or the default die-with-SIGTERM) runs last.
A raising action is logged and skipped — one broken hook must not eat
the preemption window of the others.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")

# canonical priorities: state first (it needs the grace window most),
# forensics second, whatever was installed before the chain last
PRIORITY_SAVE = 10
PRIORITY_DUMP = 20

_lock = threading.Lock()
_actions: List[Tuple[int, str, Callable]] = []
_prev_handler = None
_installed = False


def register(name: str, fn: Callable[[int, object], None],
             priority: int = 50) -> None:
    """Add (or replace, by name) a SIGTERM action. `fn(signum, frame)`
    runs inside the signal handler on the main thread — it must not
    block indefinitely. Lower priority runs earlier. Installs the chain
    handler on first registration (main thread only)."""
    with _lock:
        _actions[:] = [a for a in _actions if a[1] != name]
        _actions.append((priority, name, fn))
        _actions.sort(key=lambda a: (a[0], a[1]))
    install()


def unregister(name: str) -> None:
    with _lock:
        _actions[:] = [a for a in _actions if a[1] != name]


def actions() -> List[Tuple[int, str, Callable]]:
    with _lock:
        return list(_actions)


def _handler(signum, frame):
    for _, name, fn in actions():
        try:
            fn(signum, frame)
        except Exception:
            logger.exception("SIGTERM action %r failed", name)
    prev = _prev_handler
    if callable(prev):
        prev(signum, frame)
    elif prev != signal.SIG_IGN:
        # die with SIGTERM semantics so parents/timeouts see the real
        # cause, not a clean exit
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def install() -> bool:
    """Install the chain handler (idempotent). Re-installs when someone
    else replaced the handler since (tests save/restore handlers around
    themselves; the chain must survive that). Returns True when the
    chain handler is the live SIGTERM handler after the call."""
    global _prev_handler, _installed
    if threading.current_thread() is not threading.main_thread():
        logger.warning("SIGTERM chain requires the main thread; "
                       "skipping signal installation")
        return False
    current = signal.getsignal(signal.SIGTERM)
    if current is _handler:
        return True
    try:
        signal.signal(signal.SIGTERM, _handler)
    except ValueError:
        logger.warning("SIGTERM chain installation failed", exc_info=True)
        return False
    _prev_handler = current
    _installed = True
    return True


def installed() -> bool:
    return signal.getsignal(signal.SIGTERM) is _handler
