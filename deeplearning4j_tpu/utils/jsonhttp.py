"""Tiny shared HTTP scaffolding for the framework's servers (k-NN
serving, training UI, embedding parameter server, Keras-backend entry
point). One place for the Content-Length / parse / respond / error
boilerplate the four servers would otherwise each re-implement."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple

# handler contract: fn(path, body_bytes, headers) ->
#   (status, content_type, payload_bytes) or None for "no such route"
Handler = Callable[[str, bytes, dict], Optional[Tuple[int, str, bytes]]]


def json_response(obj, code: int = 200) -> Tuple[int, str, bytes]:
    return code, "application/json", json.dumps(obj).encode()


def html_response(text: str, code: int = 200) -> Tuple[int, str, bytes]:
    return code, "text/html", text.encode()


class JsonHttpServer:
    """Threaded HTTP server with pluggable GET/POST handlers.

    Handlers may raise: the error is returned as a 400 JSON body and the
    server keeps serving (a malformed request must never kill a
    dashboard/serving process)."""

    def __init__(self, *, get: Optional[Handler] = None,
                 post: Optional[Handler] = None, port: int = 0):
        self._get = get
        self._post = post
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        outer = self

        class _H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _dispatch(self, handler: Optional[Handler]):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b""
                try:
                    out = handler(self.path, body, dict(self.headers)) \
                        if handler else None
                    if out is None:
                        out = json_response({"error": "not found"}, 404)
                except Exception as e:  # keep serving
                    out = json_response(
                        {"error": f"{type(e).__name__}: {e}"}, 400)
                code, ctype, payload = out
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._dispatch(outer._get)

            def do_POST(self):
                self._dispatch(outer._post)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), _H)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def join(self):
        if self._thread is not None:
            self._thread.join()
