"""Meta-checks that documentation claims stay true.

Round-4 verdict finding: a docstring cited an equivalence test that did
not exist ("manufactured verification"). This sweep greps every source
docstring/comment for `tests/<file>.py` citations and fails if any cited
file is missing — a claim of test coverage must point at a real test."""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PAT = re.compile(r"tests/([A-Za-z0-9_]+\.py)")


def _source_files():
    for root, dirs, files in os.walk(os.path.join(REPO, "deeplearning4j_tpu")):
        dirs[:] = [d for d in dirs if not d.startswith("__pycache__")]
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(root, f)
    for extra in ("bench.py", "__graft_entry__.py"):
        p = os.path.join(REPO, extra)
        if os.path.exists(p):
            yield p


def test_cited_test_files_exist():
    missing = []
    for path in _source_files():
        with open(path, encoding="utf-8") as f:
            text = f.read()
        for m in PAT.finditer(text):
            cited = os.path.join(REPO, "tests", m.group(1))
            if not os.path.exists(cited):
                missing.append(f"{os.path.relpath(path, REPO)} cites "
                               f"{m.group(0)}")
    assert not missing, "dangling test citations:\n" + "\n".join(missing)


def test_bench_vs_baseline_self_reports_trajectory():
    """bench.py's vs_baseline must come from the newest committed
    BENCH_r*.json (per-workload speedup ratios), not a hardcoded null —
    the perf trajectory is self-reporting."""
    import sys

    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)

    name, prior = bench._prior_bench()
    if name is None:  # fresh clone without committed bench rounds
        assert bench._vs_baseline({"resnet50": {"value": 1.0}}, "cpu") is None
        return
    assert prior["workloads"]
    wl, entry = next(iter(prior["workloads"].items()))
    doubled = {wl: {"value": entry["value"] * 2}}
    vs = bench._vs_baseline(doubled, prior.get("backend"))
    assert vs["source"] == name
    assert abs(vs["speedup"][wl] - 2.0) < 1e-6
    # cross-backend ratios would be nonsense — omitted, with the reason
    mism = bench._vs_baseline(doubled, "not-" + str(prior.get("backend")))
    assert "speedup" not in mism and "mismatch" in mism["note"]


def test_bench_ab_refuses_mid_run_disabled_kernel():
    """_run_ab must not report a variant under the kernel's name when the
    SPI auto-disabled the helper mid-run (fn raised, layers fell back):
    that number is builtin throughput. Kill-switch state is restored."""
    import sys

    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    from deeplearning4j_tpu.ops.helpers import (
        _HELPERS,
        helper_enabled,
        register_helper,
        set_helper_enabled,
    )

    register_helper("_ab_test", lambda: None, name="scratch")
    try:
        def run(on):
            if on:  # simulate the SPI guard disabling a raising helper
                set_helper_enabled("_ab_test", False)
            return 1.0

        results, errors = bench._run_ab(
            run, [("kern", True), ("builtin", False)], ("_ab_test",))
        assert "kern" not in results
        assert "disabled mid-run" in errors["kern"]
        assert results["builtin"] == 1.0
        assert helper_enabled("_ab_test") is True  # restored
    finally:
        _HELPERS.pop("_ab_test", None)
