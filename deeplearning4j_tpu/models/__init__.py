"""Model zoo: reference workload architectures built on the config DSL
(BASELINE.md configs: LeNet-MNIST, ResNet-50, VGG16, GravesLSTM char-rnn).
"""

from deeplearning4j_tpu.models.lenet import lenet_conf, lenet_network
from deeplearning4j_tpu.models.resnet import (
    resnet_conf,
    resnet50_conf,
    resnet50_network,
    tiny_resnet_conf,
)
