"""MNIST dataset: fetcher + iterator.

Analog of the reference's MnistDataSetIterator / MnistDataFetcher /
MnistFetcher (deeplearning4j-core datasets/iterator/impl/ + base/ — download
with local cache, idx-format parsing). Capability-equivalent behavior:

- looks for cached idx files under ~/.deeplearning4j_tpu/mnist (or $DL4J_TPU_DATA)
- downloads if absent (standard mirrors)
- if the environment has no egress (this CI), falls back to a DETERMINISTIC
  synthetic digit dataset: procedural 28x28 glyphs with random shift/noise/
  thickness jitter. It is honestly labeled via `source` so benchmarks can
  report which data they ran on; the training dynamics (conv net reaches
  >95% quickly) make it a faithful stand-in for pipeline/e2e tests.
"""

from __future__ import annotations

import gzip
import os
import struct
import urllib.request
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator

_MIRRORS = [
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
]
_FILES = {
    "train_images": "train-images-idx3-ubyte.gz",
    "train_labels": "train-labels-idx1-ubyte.gz",
    "test_images": "t10k-images-idx3-ubyte.gz",
    "test_labels": "t10k-labels-idx1-ubyte.gz",
}


def _cache_dir() -> Path:
    root = os.environ.get("DL4J_TPU_DATA", os.path.expanduser("~/.deeplearning4j_tpu"))
    d = Path(root) / "mnist"
    d.mkdir(parents=True, exist_ok=True)
    return d


def _read_idx_images(path: Path) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"bad idx image magic {magic}")
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def _read_idx_labels(path: Path) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"bad idx label magic {magic}")
        return np.frombuffer(f.read(), dtype=np.uint8)


def _try_download(fname: str, dest: Path, timeout: float = 20.0) -> bool:
    for mirror in _MIRRORS:
        try:
            urllib.request.urlretrieve(mirror + fname, dest)  # noqa: S310
            return True
        except Exception:
            continue
    return False


# -- synthetic fallback ------------------------------------------------------
# 7x5 bitmap font for digits 0-9, upscaled to 28x28 with jitter.
_GLYPHS = {
    0: ["01110", "10001", "10001", "10001", "10001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00110", "01000", "10000", "11111"],
    3: ["11110", "00001", "00001", "01110", "00001", "00001", "11110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}


def synthetic_mnist(n: int, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped synthetic digits: [n, 28, 28] uint8 + [n]."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    images = np.zeros((n, 28, 28), dtype=np.uint8)
    base = {}
    for d, rows in _GLYPHS.items():
        g = np.array([[int(c) for c in r] for r in rows], dtype=np.float32)
        # upscale 7x5 -> 21x15
        g = np.kron(g, np.ones((3, 3), np.float32))
        base[d] = g
    for i in range(n):
        g = base[int(labels[i])]
        canvas = np.zeros((28, 28), np.float32)
        dy = rng.integers(0, 28 - g.shape[0] + 1)
        dx = rng.integers(0, 28 - g.shape[1] + 1)
        intensity = rng.uniform(0.6, 1.0)
        canvas[dy : dy + g.shape[0], dx : dx + g.shape[1]] = g * intensity
        canvas += rng.normal(0, 0.05, (28, 28)).clip(0, 1) * 0.3
        images[i] = (canvas.clip(0, 1) * 255).astype(np.uint8)
    return images, labels.astype(np.int64)


class MnistDataFetcher:
    """Load (download/cache/synthesize) the MNIST arrays."""

    def __init__(self, allow_download: bool = True, synthetic_fallback: bool = True,
                 synthetic_train: int = 12800, synthetic_test: int = 2560):
        self.allow_download = allow_download
        self.synthetic_fallback = synthetic_fallback
        self.synthetic_train = synthetic_train
        self.synthetic_test = synthetic_test
        self.source = None  # "cache" | "download" | "synthetic"

    def load(self, train: bool) -> Tuple[np.ndarray, np.ndarray]:
        d = _cache_dir()
        img_key = "train_images" if train else "test_images"
        lab_key = "train_labels" if train else "test_labels"
        img_path = d / _FILES[img_key]
        lab_path = d / _FILES[lab_key]
        if not (img_path.exists() and lab_path.exists()) and self.allow_download:
            ok = _try_download(_FILES[img_key], img_path) and _try_download(
                _FILES[lab_key], lab_path
            )
            if ok:
                self.source = "download"
        if img_path.exists() and lab_path.exists():
            self.source = self.source or "cache"
            return _read_idx_images(img_path), _read_idx_labels(lab_path)
        if not self.synthetic_fallback:
            raise IOError("MNIST unavailable: no cache, no network")
        self.source = "synthetic"
        n = self.synthetic_train if train else self.synthetic_test
        return synthetic_mnist(n, seed=1 if train else 2)


class MnistDataSetIterator(ListDataSetIterator):
    """Reference-shaped API: MnistDataSetIterator(batch, train, seed).
    Features are flattened 784 f32 in [0,1] (matching the reference's
    MnistDataFetcher normalization); use InputType.convolutional_flat in the
    network conf to reshape for conv stacks."""

    def __init__(self, batch: int, train: bool = True, seed: int = 6,
                 shuffle: Optional[bool] = None, num_examples: Optional[int] = None,
                 fetcher: Optional[MnistDataFetcher] = None):
        fetcher = fetcher or MnistDataFetcher()
        images, labels = fetcher.load(train)
        self.source = fetcher.source
        if num_examples is not None:
            images, labels = images[:num_examples], labels[:num_examples]
        x = images.reshape(images.shape[0], -1).astype(np.float32) / 255.0
        y = np.zeros((labels.shape[0], 10), np.float32)
        y[np.arange(labels.shape[0]), labels] = 1.0
        super().__init__(DataSet(x, y), batch,
                         shuffle=train if shuffle is None else shuffle, seed=seed)
