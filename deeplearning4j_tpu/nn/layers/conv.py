"""Convolution, pooling, padding and global pooling layers (NHWC).

Reference impls: nn/layers/convolution/ConvolutionLayer.java:177-201
(im2col -> reshape -> Nd4j.gemm) and the cuDNN helper plugin
(deeplearning4j-cuda CudnnConvolutionHelper.java:345). Here the conv lowers
to lax.conv_general_dilated which XLA tiles straight onto the MXU — no
explicit im2col buffer and no helper SPI needed for the base path; Pallas
kernels can still override via ops/ when profiling says so.

Pooling: SubsamplingLayer (max/avg/sum/pnorm) -> lax.reduce_window
(reference: nn/layers/convolution/subsampling/SubsamplingLayer.java,
CudnnSubsamplingHelper). Gradients come from autodiff, which XLA rewrites
to the select-and-scatter form itself.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.layers import ConvolutionMode, PoolingType
from deeplearning4j_tpu.nn.layers.core import apply_dropout
from deeplearning4j_tpu.nn.layers.registry import LayerContext, register_layer
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import apply_activation
from deeplearning4j_tpu.ops.helpers import HelperError, get_helper

_DIMS2D = ("NHWC", "HWIO", "NHWC")


def _padding_2d(conf) -> object:
    if conf.convolution_mode == ConvolutionMode.SAME:
        return "SAME"
    p = conf.padding
    return [(int(p[0]), int(p[0])), (int(p[1]), int(p[1]))]


# -- 2D convolution ----------------------------------------------------------

def conv_init(key, conf: L.ConvolutionLayer, dtype):
    kh, kw_ = int(conf.kernel_size[0]), int(conf.kernel_size[1])
    fan_in = conf.n_in * kh * kw_
    fan_out = conf.n_out * kh * kw_
    k1, _ = jax.random.split(key)
    W = init_weights(k1, (kh, kw_, conf.n_in, conf.n_out), fan_in, fan_out,
                     conf.weight_init, conf.dist, dtype)
    out = {"W": W}
    if conf.has_bias:
        out["b"] = jnp.full((conf.n_out,), conf.bias_init or 0.0, dtype)
    return out


def conv_forward(conf: L.ConvolutionLayer, params, x, ctx: LayerContext):
    x = apply_dropout(x, conf.dropout, ctx)
    strides = tuple(int(s) for s in conf.stride)
    # vendor-kernel plugin point (the CudnnConvolutionHelper analog): a
    # registered conv kernel — e.g. the Pallas conv+BN-stats epilogue
    # fusion (ops/pallas_conv_bn.py) — takes over when it supports this
    # configuration; a helper that raises is disabled by the SPI and the
    # built-in XLA lowering below runs instead
    z = None
    helper = get_helper(
        "conv2d",
        kernel=tuple(int(k) for k in conf.kernel_size),
        stride=strides,
        dilation=tuple(int(d) for d in conf.dilation),
        same=conf.convolution_mode == ConvolutionMode.SAME,
        has_bias=conf.has_bias,
        activation=conf.activation,
        dtype=x.dtype,
        n_in=int(x.shape[-1]),
        n_out=int(conf.n_out),
        x_shape=tuple(int(d) for d in x.shape),
        training=ctx.training,
    )
    if helper is not None:
        try:
            z = helper(x, params["W"].astype(x.dtype), strides=strides)
        except HelperError:
            z = None
    if z is None:
        z = lax.conv_general_dilated(
            x,
            params["W"].astype(x.dtype),
            window_strides=strides,
            padding=_padding_2d(conf),
            rhs_dilation=tuple(int(d) for d in conf.dilation),
            dimension_numbers=_DIMS2D,
        )
    if conf.has_bias:
        z = z + params["b"].astype(z.dtype)
    return apply_activation(conf.activation, z, key=ctx.rng, training=ctx.training), None


def conv_order(conf):
    return ("W", "b") if conf.has_bias else ("W",)


register_layer(L.ConvolutionLayer, conv_init, conv_forward, order_fn=conv_order)


# -- 1D convolution over time ------------------------------------------------

def conv1d_init(key, conf: L.Convolution1DLayer, dtype):
    k = int(conf.kernel_size)
    fan_in = conf.n_in * k
    fan_out = conf.n_out * k
    k1, _ = jax.random.split(key)
    W = init_weights(k1, (k, conf.n_in, conf.n_out), fan_in, fan_out,
                     conf.weight_init, conf.dist, dtype)
    out = {"W": W}
    if conf.has_bias:
        out["b"] = jnp.full((conf.n_out,), conf.bias_init or 0.0, dtype)
    return out


def conv1d_forward(conf: L.Convolution1DLayer, params, x, ctx: LayerContext):
    # x: [batch, time, nIn]
    x = apply_dropout(x, conf.dropout, ctx)
    if conf.convolution_mode == ConvolutionMode.SAME:
        padding = "SAME"
    else:
        padding = [(int(conf.padding), int(conf.padding))]
    z = lax.conv_general_dilated(
        x, params["W"].astype(x.dtype),
        window_strides=(int(conf.stride),),
        padding=padding,
        dimension_numbers=("NWC", "WIO", "NWC"),
    )
    if conf.has_bias:
        z = z + params["b"].astype(z.dtype)
    return apply_activation(conf.activation, z, key=ctx.rng, training=ctx.training), None


register_layer(L.Convolution1DLayer, conv1d_init, conv1d_forward, order_fn=conv_order)


# -- pooling -----------------------------------------------------------------

def _pool(x, pooling_type, window, strides, padding, pnorm):
    """reduce_window pooling over explicitly-windowed axes. window/strides
    are full-rank tuples (1s for batch/channel)."""
    if pooling_type == PoolingType.MAX:
        neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, neg_inf, lax.max, window, strides, padding)
    if pooling_type == PoolingType.SUM:
        return lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
    if pooling_type == PoolingType.AVG:
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        n = 1
        for w in window:
            n *= w
        return s / n
    if pooling_type == PoolingType.PNORM:
        p = float(pnorm)
        s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, padding)
        return s ** (1.0 / p)
    raise ValueError(f"unknown pooling type {pooling_type!r}")


def _no_params(key, conf, dtype):
    return {}


def subsampling_forward(conf: L.SubsamplingLayer, params, x, ctx: LayerContext):
    window = (1, int(conf.kernel_size[0]), int(conf.kernel_size[1]), 1)
    strides = (1, int(conf.stride[0]), int(conf.stride[1]), 1)
    if conf.convolution_mode == ConvolutionMode.SAME:
        padding = "SAME"
    else:
        p = conf.padding
        padding = [(0, 0), (int(p[0]), int(p[0])), (int(p[1]), int(p[1])), (0, 0)]
    return _pool(x, conf.pooling_type, window, strides, padding, conf.pnorm), None


register_layer(L.SubsamplingLayer, _no_params, subsampling_forward)


def subsampling1d_forward(conf: L.Subsampling1DLayer, params, x, ctx: LayerContext):
    window = (1, int(conf.kernel_size), 1)
    strides = (1, int(conf.stride), 1)
    if conf.convolution_mode == ConvolutionMode.SAME:
        padding = "SAME"
    else:
        padding = [(0, 0), (int(conf.padding), int(conf.padding)), (0, 0)]
    return _pool(x, conf.pooling_type, window, strides, padding, conf.pnorm), None


register_layer(L.Subsampling1DLayer, _no_params, subsampling1d_forward)


# -- zero padding ------------------------------------------------------------

def zero_padding_forward(conf: L.ZeroPaddingLayer, params, x, ctx: LayerContext):
    pt, pb, pl, pr = (int(v) for v in conf.padding)
    return jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0))), None


register_layer(L.ZeroPaddingLayer, _no_params, zero_padding_forward)


# -- global pooling ----------------------------------------------------------

def global_pooling_forward(conf: L.GlobalPoolingLayer, params, x, ctx: LayerContext):
    """CNN input [b,h,w,c]: pool h,w. RNN input [b,t,f]: pool t, honoring the
    time mask (reference: GlobalPoolingLayer.java + MaskedReductionUtil)."""
    pt = conf.pooling_type
    if x.ndim == 4:
        axes = (1, 2)
        mask = None
    elif x.ndim == 3:
        axes = (1,)
        mask = ctx.mask  # [batch, time]
    else:
        raise ValueError(f"global pooling expects 3d/4d input, got shape {x.shape}")

    if mask is not None:
        m = mask[..., None].astype(x.dtype)
        if pt == PoolingType.MAX:
            x = jnp.where(m > 0, x, -jnp.inf)
        else:
            x = x * m
    if pt == PoolingType.MAX:
        return jnp.max(x, axis=axes), None
    if pt == PoolingType.SUM:
        return jnp.sum(x, axis=axes), None
    if pt == PoolingType.AVG:
        if mask is not None:
            denom = jnp.maximum(jnp.sum(mask, axis=1, keepdims=False), 1.0)[..., None]
            return jnp.sum(x, axis=axes) / denom, None
        return jnp.mean(x, axis=axes), None
    if pt == PoolingType.PNORM:
        p = float(conf.pnorm)
        return jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p), None
    raise ValueError(f"unknown pooling type {pt!r}")


register_layer(L.GlobalPoolingLayer, _no_params, global_pooling_forward)
