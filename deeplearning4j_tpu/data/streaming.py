"""Streaming ingestion (reference: deeplearning4j-scaleout/dl4j-streaming —
Kafka+Camel routes feeding NDArray batches into training).

Broker-agnostic TPU-native shape: a StreamingDataSetIterator pulls
(features, labels) payloads from any source callable/iterable on a
background thread into a bounded buffer; training consumes DataSets at
device speed and blocks only when the stream lags. A Kafka/PubSub consumer
plugs in as the ``source`` — the framework sees the same iterator SPI the
rest of data/ uses."""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Optional, Tuple, Union

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    PIPELINE_THREAD_PREFIX,
    DataSetIterator,
    _close_run,
    _get_abortable,
    _put_abortable,
)

_SENTINEL = object()


class StreamingDataSetIterator(DataSetIterator):
    """Wraps a stream of (features, labels) into the DataSetIterator SPI.

    source: an iterable OR a zero-arg callable returning the next payload
            (None = end of stream). Payloads may be (x, y) tuples or
            DataSets.
    buffer_size: bounded prefetch depth — backpressure to the producer.
    """

    def __init__(self,
                 source: Union[Iterable, Callable[[], Optional[Tuple]]],
                 buffer_size: int = 16):
        self.source = source
        self.buffer_size = int(buffer_size)
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._stop: Optional[threading.Event] = None

    def reset(self):
        """No-op: the fit loop resets after each epoch, which is legal at
        end-of-stream. Actually REUSING the iterator (epochs > 1, or a
        second fit) raises in __iter__ — a stream has no beginning to go
        back to (reference dl4j-streaming semantics)."""

    def _consumed_guard(self):
        if getattr(self, "_consumed", False):
            raise RuntimeError(
                "stream already consumed and cannot be reset; re-create "
                "the iterator with a new source")
        self._consumed = True

    def _pump(self):
        try:
            if callable(self.source):
                while not self._stop.is_set():
                    item = self.source()
                    if item is None:
                        break
                    if not _put_abortable(self._q, item, self._stop):
                        return
            else:
                for item in self.source:
                    if not _put_abortable(self._q, item, self._stop):
                        return
        except BaseException as e:  # surface in the consumer
            self._error = e
        finally:
            _put_abortable(self._q, _SENTINEL, self._stop)

    def __iter__(self):
        self._consumed_guard()
        self._q = queue.Queue(maxsize=self.buffer_size)
        self._error = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump, daemon=True,
            name=f"{PIPELINE_THREAD_PREFIX}-stream")
        self._thread.start()
        try:
            while True:
                item = _get_abortable(self._q, self._stop)
                if item is None or item is _SENTINEL:
                    if self._error is not None:
                        raise self._error
                    return
                if isinstance(item, DataSet):
                    yield item
                else:
                    x, y = item
                    yield DataSet(np.asarray(x), np.asarray(y))
        finally:
            # close-on-break: a consumer that stops mid-stream must not
            # leave the pump blocked on a full buffer forever
            _close_run(self._q, self._stop, [self._thread])

    def close(self):
        if self._thread is not None and self._stop is not None:
            _close_run(self._q, self._stop, [self._thread])
