"""Preemption-aware checkpointing (train/checkpoint.py) + profiler hook
(utils/profiler.py)."""

import os
import signal

import numpy as np
import pytest

from deeplearning4j_tpu.train.checkpoint import CheckpointListener


def _net_and_data(seed=3):
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed).updater("adam")
            .learning_rate(0.02).list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((48, 5)).astype(np.float32)
    y = np.zeros((48, 3), np.float32)
    y[np.arange(48), rng.integers(0, 3, 48)] = 1.0
    return net, x, y


def test_periodic_save_retention_and_resume(tmp_path):
    ckdir = str(tmp_path / "ckpts")
    net, x, y = _net_and_data()
    listener = CheckpointListener(ckdir, every_n_iterations=2,
                                  every_n_epochs=None, keep_last=2)
    net.set_listeners(listener)
    net.fit(x, y, batch_size=8, epochs=2, async_prefetch=False)  # 12 iters

    zips = [f for f in os.listdir(ckdir) if f.endswith(".zip")]
    assert len(zips) == 2  # retention pruned the older ones

    restored, meta = CheckpointListener.restore_latest(ckdir)
    assert meta["iteration"] == restored.iteration
    assert meta["reason"] == "schedule"
    # the final save fired on the last scheduled iteration; prove the
    # restored weights match the live net by saving it again now and
    # comparing outputs at the SAME iteration
    final = listener.save(net, reason="manual")
    from deeplearning4j_tpu.utils.model_serializer import load_model

    same_iter = load_model(final)
    np.testing.assert_allclose(
        np.asarray(same_iter.output(x)), np.asarray(net.output(x)),
        rtol=1e-5, atol=1e-6)
    # resumed model: training continues seamlessly from the checkpoint
    restored.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)
    assert restored.iteration == meta["iteration"] + 6


def test_restore_latest_missing_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        CheckpointListener.restore_latest(str(tmp_path / "nothing"))


def test_preemption_sigterm_saves(tmp_path):
    """SIGTERM triggers a synchronous save before the previous handler —
    the TPU-pool preemption contract."""
    ckdir = str(tmp_path / "pre")
    net, x, y = _net_and_data()
    fired = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: fired.append(s))
    try:
        listener = CheckpointListener(ckdir, every_n_iterations=None,
                                      every_n_epochs=None,
                                      save_on_preemption=True)
        net.set_listeners(listener)
        net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
        assert not os.path.exists(os.path.join(ckdir, "latest.json"))
        os.kill(os.getpid(), signal.SIGTERM)  # delivered synchronously
        assert os.path.exists(os.path.join(ckdir, "latest.json"))
        restored, meta = CheckpointListener.restore_latest(ckdir)
        assert meta["reason"] == "preemption"
        assert restored.iteration == net.iteration
        assert fired, "previous SIGTERM handler must still run"
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_profiler_listener_collects_summary(tmp_path):
    """ProfilerListener captures a trace window and parses an op summary
    (device plane present even on CPU)."""
    from deeplearning4j_tpu.utils.profiler import ProfilerListener

    net, x, y = _net_and_data()
    lines = []
    listener = ProfilerListener(str(tmp_path / "prof"), start_iteration=2,
                                n_iterations=2, print_fn=lines.append)
    net.set_listeners(listener)
    net.fit(x, y, batch_size=8, epochs=2, async_prefetch=False)
    assert not listener._active
    # CPU planes are named "/device:CPU:..." — summary may be empty if the
    # runtime exposes no XLA Ops line, but the trace must have been
    # captured and parsed without error
    from deeplearning4j_tpu.utils.profiler import latest_xplane

    assert latest_xplane(str(tmp_path / "prof")) is not None


def test_op_family_aggregation():
    """op_family collapses HLO instance names into the PROFILE_*.md
    grouping; family_summary aggregates times across instances."""
    from deeplearning4j_tpu.utils.profiler import family_summary, op_family

    assert op_family("fusion.123") == "fusion"
    assert op_family("%convert_reduce_fusion.5") == "convert_reduce_fusion"
    assert op_family("add_add_fusion") == "add_add_fusion"
    assert op_family("copy-done.7") == "copy-done"
    assert op_family("custom-call.3.1") == "custom-call"
    assert op_family("fusion.2 (param0)") == "fusion"
    rows = [("fusion.1", 0.5), ("fusion.2", 0.25),
            ("convert_reduce_fusion.9", 1.0), ("copy-done", 0.1)]
    fam = dict(family_summary(rows))
    assert fam == {"fusion": 0.75, "convert_reduce_fusion": 1.0,
                   "copy-done": 0.1}


def test_write_profile_json(tmp_path, monkeypatch):
    """profile --json artifact: op-family breakdown serialized for bench
    runs to attach mechanically."""
    import json

    from deeplearning4j_tpu.utils import profiler

    rows = [("convert_reduce_fusion.1", 0.010), ("fusion.4", 0.002),
            ("convert_reduce_fusion.2", 0.005)]
    monkeypatch.setattr(profiler, "op_summary", lambda d, top=20, **k: rows)
    out = str(tmp_path / "profile.json")
    payload = profiler.write_profile_json(str(tmp_path), out,
                                          meta={"workload": "resnet50"})
    on_disk = json.load(open(out))
    assert on_disk == json.loads(json.dumps(payload))
    assert on_disk["families_ms"]["convert_reduce_fusion"] == 15.0
    assert on_disk["families_ms"]["fusion"] == 2.0
    assert on_disk["meta"]["workload"] == "resnet50"
    assert on_disk["top_ops_ms"][0]["op"] == "convert_reduce_fusion.1"


def test_cli_profile_json(tmp_path, monkeypatch, capsys):
    """`deeplearning4j_tpu profile --log-dir D --json P` writes the
    artifact through the CLI."""
    import json

    from deeplearning4j_tpu import cli
    from deeplearning4j_tpu.utils import profiler

    rows = [("fusion.1", 0.001)]
    monkeypatch.setattr(profiler, "op_summary", lambda d, top=20, **k: rows)
    out = str(tmp_path / "p.json")
    rc = cli.main(["profile", "--log-dir", str(tmp_path), "--json", out])
    assert rc == 0
    assert json.load(open(out))["families_ms"] == {"fusion": 1.0}
