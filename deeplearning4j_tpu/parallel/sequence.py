"""Sequence/context parallelism: ring attention over a mesh axis.

NEW capability beyond the reference (SURVEY §5 "long-context: absent" —
DL4J's only long-sequence tool is truncated BPTT). For sequences too long
for one chip's HBM, the sequence axis is sharded over the mesh and
attention runs as a RING: each device holds one query block permanently
and passes its key/value block around the "seq" axis with ppermute,
accumulating attention with the online-softmax (flash-style) update so
the full [T, T] score matrix never materializes. After `p` hops every
query block has attended to every kv block; communication rides ICI
neighbor links (the pattern of Ring Attention, Liu et al.; blockwise
streaming softmax, Rabe & Staats).

All functions here are written to run under `shard_map` over a Mesh axis
named ``axis_name`` — see ``ring_self_attention`` for the user-facing
entry and tests/test_sequence_parallel.py for the 8-device CPU-mesh
equivalence proof vs single-device full attention.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

SEQ_AXIS = "seq"


def _block_attend(q, k, v, *, scale, causal, q_start, kv_start):
    """Scores of one (q-block, kv-block) pair + unnormalized streaming
    stats. q: [B, Tq, H, D]; k/v: [B, Tk, H, D]. Returns (m, l, o):
    running max [B, H, Tq], sum-exp [B, H, Tq], weighted values
    [B, Tq, H, D]."""
    # Softmax statistics live in at-least-f32 (flash convention): the
    # QK^T and PV dots keep bf16 operands on the MXU but accumulate f32
    # via preferred_element_type, so bf16 long-context inputs never
    # accumulate softmax mass in bf16 across ring hops. f64 inputs (the
    # gradient-check harness) keep full f64 statistics.
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=acc_dt) * scale
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        qpos = q_start + jnp.arange(Tq)[:, None]
        kpos = kv_start + jnp.arange(Tk)[None, :]
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    m = jnp.max(s, axis=-1)                          # [B, H, Tq] f32
    # fully-masked rows (causal, kv block entirely in the future) produce
    # -inf max; exp(-inf - -inf) would be NaN — clamp those rows
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)                          # [B, H, Tq] f32
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=acc_dt)
    return m_safe, l, o


def _merge(acc, new):
    """Online-softmax merge of two partial attention states."""
    m_a, l_a, o_a = acc
    m_n, l_n, o_n = new
    m = jnp.maximum(m_a, m_n)
    ca = jnp.exp(m_a - m)
    cn = jnp.exp(m_n - m)
    l = l_a * ca + l_n * cn
    o = (o_a * jnp.moveaxis(ca, 1, -1)[..., None]
         + o_n * jnp.moveaxis(cn, 1, -1)[..., None])
    return m, l, o


def ring_attention_sharded(q, k, v, *, axis_name: str = SEQ_AXIS,
                           causal: bool = False):
    """The shard_map body: q/k/v are LOCAL sequence blocks
    [B, T_local, H, D]; the kv block rotates around ``axis_name``.

    Device i keeps its queries; at hop s it holds kv block (i - s) mod p.
    Online-softmax accumulation makes the result exactly equal (up to
    float re-association) to full attention over the gathered sequence.
    """
    p = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    scale = jnp.sqrt(jnp.asarray(q.shape[-1], acc_dt)) ** -1
    q_start = idx * t_local

    B, T, H, D = q.shape
    # accumulators are at-least-f32 regardless of q.dtype — see _block_attend
    acc = (
        jnp.full((B, H, T), -jnp.inf, acc_dt),
        jnp.zeros((B, H, T), acc_dt),
        jnp.zeros((B, T, H, D), acc_dt),
    )
    # the accumulator becomes device-varying after the first hop; mark the
    # (device-constant) init accordingly for shard_map's axis typing
    if hasattr(lax, "pcast"):
        acc = jax.tree_util.tree_map(
            lambda a: lax.pcast(a, (axis_name,), to="varying"), acc)
    elif hasattr(lax, "pvary"):  # pre-0.9 jax
        acc = jax.tree_util.tree_map(
            lambda a: lax.pvary(a, (axis_name,)), acc)
    # static unroll over the (small, known) ring size: lets XLA overlap
    # each hop's permute with the previous hop's attention, and skips the
    # final rotation whose result nobody reads
    perm = [(j, (j + 1) % p) for j in range(p)]
    k_cur, v_cur = k, v
    for s in range(p):
        kv_owner = (idx - s) % p                # whose block we hold now
        new = _block_attend(q, k_cur, v_cur, scale=scale, causal=causal,
                            q_start=q_start, kv_start=kv_owner * t_local)
        acc = _merge(acc, new)
        if s < p - 1:  # last hop: kv would never be read again
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    m, l, o = acc
    l = jnp.maximum(l, 1e-20)
    out = o / jnp.moveaxis(l, 1, -1)[..., None]
    return out.astype(q.dtype)


def full_attention(q, k, v, *, causal: bool = False):
    """Single-device reference: ordinary softmax attention
    ([B, T, H, D] inputs, head-batched)."""
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    scale = jnp.sqrt(jnp.asarray(q.shape[-1], acc_dt)) ** -1
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=acc_dt) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -jnp.inf)
    a = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", a.astype(v.dtype), v,
                     preferred_element_type=acc_dt)
    return out.astype(q.dtype)


def ring_self_attention(x, wq, wk, wv, wo, *, mesh: Mesh,
                        n_heads: int, causal: bool = False,
                        axis_name: str = SEQ_AXIS):
    """Sequence-parallel multi-head self-attention over a Mesh.

    x: [B, T, E] with T divisible by the ``axis_name`` mesh size. The
    projections are computed on the local block (no communication); only
    k/v blocks travel the ring."""
    E = x.shape[-1]
    D = E // n_heads

    def body(xb):
        B, Tl = xb.shape[0], xb.shape[1]
        q = (xb @ wq).reshape(B, Tl, n_heads, D)
        k = (xb @ wk).reshape(B, Tl, n_heads, D)
        v = (xb @ wv).reshape(B, Tl, n_heads, D)
        o = ring_attention_sharded(q, k, v, axis_name=axis_name,
                                   causal=causal)
        return o.reshape(B, Tl, E) @ wo

    from deeplearning4j_tpu.parallel.mesh import shard_map_fn

    shard_map = shard_map_fn()

    spec_x = PartitionSpec(None, axis_name, None)
    return shard_map(
        body, mesh=mesh,
        in_specs=(spec_x,),
        out_specs=spec_x,
    )(x)
