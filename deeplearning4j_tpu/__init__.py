"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A from-scratch reimplementation of the *capabilities* of Deeplearning4j
(reference surveyed in SURVEY.md) designed idiomatically for TPUs:

- declarative layer/graph configuration DSL with JSON round-trip
  (reference: deeplearning4j-nn/.../nn/conf/NeuralNetConfiguration.java)
- pure-functional layer forward passes compiled by XLA; gradients via
  autodiff instead of hand-written backprop
  (reference: deeplearning4j-nn/.../nn/layers/*)
- one jitted train step = forward + loss + grad + normalization + fused
  updater, with buffer donation
  (reference: Solver/StochasticGradientDescent + BaseMultiLayerUpdater)
- data parallelism via jax.sharding Mesh + per-step gradient psum over ICI
  (reference: deeplearning4j-scaleout ParallelWrapper / Spark averaging)
- Pallas kernels where XLA's defaults need help
  (reference: deeplearning4j-cuda cuDNN helper plugins)

The public API deliberately mirrors the reference's concept names
(MultiLayerConfiguration, ComputationGraph, Updater, Evaluation, ...) so a
DL4J user can find everything they know, while the execution model is
TPU-first throughout.
"""

__version__ = "0.1.0"

import logging as _logging

# Library-logging contract: the framework logs to the
# "deeplearning4j_tpu" logger everywhere, and library code must not
# print or configure handlers on its own — the NullHandler silences the
# "No handlers could be found" fallback until the APP opts in (below, or
# with its own logging config).
_logging.getLogger("deeplearning4j_tpu").addHandler(_logging.NullHandler())


def configure_logging(level=_logging.INFO, json_lines: bool = False,
                      stream=None):
    """Opt-in log output for applications and CLIs.

    Plain mode attaches a conventional stderr handler. `json_lines=True`
    emits one JSON object per record (ts/level/logger/message, plus
    `trace_id`/`span_id` — the active distributed-tracing context when a
    span is open on the logging thread, empty strings otherwise — so one
    trace id greps across logs, span exports, flight-recorder dumps and
    histogram exemplars) so log aggregators get structured records
    without a parsing layer. Calling again replaces the handler installed
    by the previous call (idempotent — safe from notebooks/REPLs)."""
    import json as _json
    import time as _time

    from deeplearning4j_tpu.utils import tracing as _tracing

    logger = _logging.getLogger("deeplearning4j_tpu")
    for h in list(logger.handlers):
        if getattr(h, "_dl4j_tpu_configured", False):
            logger.removeHandler(h)
    handler = _logging.StreamHandler(stream)
    if json_lines:
        class _JsonFormatter(_logging.Formatter):
            def format(self, record):
                # format() runs on the emitting thread, so the active
                # span context here IS the one the message belongs to
                ctx = _tracing.current_context()
                doc = {
                    "ts": round(record.created, 3),
                    "iso": _time.strftime(
                        "%Y-%m-%dT%H:%M:%S",
                        _time.gmtime(record.created)) + "Z",
                    "level": record.levelname,
                    "logger": record.name,
                    "message": record.getMessage(),
                    "trace_id": ctx.trace_id if ctx is not None else "",
                    "span_id": (format(ctx.span_id, "016x")
                                if ctx is not None else ""),
                }
                if record.exc_info:
                    doc["exc"] = self.formatException(record.exc_info)
                return _json.dumps(doc)

        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(_logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    handler._dl4j_tpu_configured = True
    logger.addHandler(handler)
    logger.setLevel(level)
    return logger


import os as _os

if _os.environ.get("DL4J_LOCKCHECK", "") == "1":
    # arm the lock-order sanitizer BEFORE any framework module runs its
    # module-level lock constructions (utils.metrics, utils.health) so
    # those locks are traced too; off-path cost is zero — the import
    # below is what patches, and it only happens under the env flag
    from deeplearning4j_tpu.utils import locktrace as _locktrace  # noqa: F401

from deeplearning4j_tpu.common.dtypes import PrecisionPolicy, default_policy
