"""DL4J model-zip importer — the migration path from the reference.

Reference format (util/ModelSerializer.java:40,79-118): a zip holding
  configuration.json  — MultiLayerConfiguration Jackson JSON (layer confs
                        wrapped by type name, Layer.java:47-48 WRAPPER_OBJECT)
  coefficients.bin    — Nd4j.write of the single flattened f32/f64 params
                        row vector (MultiLayerNetwork.java:102 flattenedParams)
  updaterState.bin    — optional flattened updater state
                        (ModelSerializer.java:107-119 write, :148 restore
                        via restoreMultiLayerNetwork(file, loadUpdater)).
                        Layout: the BaseMultiLayerUpdater state view —
                        per contiguous UpdaterBlock (params sharing one
                        updater configuration, BaseMultiLayerUpdater.java:
                        63-104), the updater's state components over the
                        block's params in flat order; two-component
                        updaters split the block view in halves (nd4j
                        AdamUpdater: m = first half, v = second; AdaDelta
                        msg/msdx), single-component (Nesterovs v, AdaGrad
                        historicalGradient, RMSProp lastGradient) use the
                        whole view. BN running mean/var are params with
                        updater NONE in DL4J (stateSize 0) and therefore
                        break block contiguity; differing effective
                        learning rates (bias_learning_rate overrides)
                        break blocks too (UpdaterUtils
                        updaterConfigurationsEqual).

Flat layouts mirrored from nn/params/* (the load-bearing part):
  Dense/Output/RnnOutput/Embedding (DefaultParamInitializer): W [nIn,nOut]
    f-order, then b [nOut].
  Convolution (ConvolutionParamInitializer:140): W [nOut,nIn,kh,kw]
    f-order, then b [nOut] -> transposed to this framework's HWIO.
  BatchNormalization (BatchNormalizationParamInitializer:56-70): gamma,
    beta, then the running mean/var — params in DL4J, STATE here.
  LSTM (LSTMParamInitializer:init): W [nIn,4H], RW [H,4H], b [4H], gate
    blocks ordered [I,F,O,G] where I is the tanh candidate and G the
    sigmoid input gate (LSTMHelpers.java:64,213-215); this framework
    orders blocks [input gate, forget, candidate, output], so columns
    permute [G,F,I,O] -> [i,f,g,o] on import.
  GravesLSTM (GravesLSTMParamInitializer): RW [H,4H+3] with peephole
    columns [wFF,wOO,wGG] appended (LSTMHelpers.java:104-115); wGG feeds
    the sigmoid input gate -> pI, wFF -> pF, wOO -> pO.

Binary array format: the era's Nd4j.write(arr, DataOutputStream) —
big-endian: shape-info buffer (int count, then rank/shape/stride/offset/
elementWiseStride/order ints) followed by a UTF-8 dtype tag and the raw
elements. write_nd4j_array produces the same layout (fixture generation +
export interop).
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
from typing import List, Tuple

import numpy as np

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration


# -- Nd4j legacy binary array format ----------------------------------------

_DTYPES = {"FLOAT": ("f", 4), "DOUBLE": ("d", 8)}


def write_nd4j_array(arr: np.ndarray, stream) -> None:
    """Serialize in the legacy Nd4j.write layout (big-endian, Java
    DataOutputStream conventions). Arrays are written as 2-d row vectors
    in 'c' order with contiguous strides, matching flattened params."""
    arr = np.asarray(arr)
    if arr.ndim == 1:
        arr = arr[None, :]
    rank = arr.ndim
    shape = list(arr.shape)
    # c-order strides in elements
    strides = []
    acc = 1
    for d in reversed(shape):
        strides.insert(0, acc)
        acc *= d
    info = [rank] + shape + strides + [0, 1, ord("c")]
    stream.write(struct.pack(">i", len(info)))
    stream.write(struct.pack(f">{len(info)}i", *info))
    if arr.dtype == np.float64:
        tag, be = "DOUBLE", ">f8"
    else:
        arr = arr.astype(np.float32)
        tag, be = "FLOAT", ">f4"
    tag_b = tag.encode()
    stream.write(struct.pack(">H", len(tag_b)) + tag_b)  # writeUTF
    stream.write(np.ascontiguousarray(arr.reshape(-1)).astype(be).tobytes())


def read_nd4j_array(stream) -> np.ndarray:
    """Parse the legacy Nd4j.write layout back into numpy (row vector)."""
    (n_info,) = struct.unpack(">i", stream.read(4))
    info = struct.unpack(f">{n_info}i", stream.read(4 * n_info))
    rank = info[0]
    shape = list(info[1 : 1 + rank])
    order = chr(info[-1])
    (tag_len,) = struct.unpack(">H", stream.read(2))
    tag = stream.read(tag_len).decode()
    if tag not in _DTYPES:
        raise ValueError(f"unsupported nd4j dtype tag {tag!r}")
    _, width = _DTYPES[tag]
    count = int(np.prod(shape)) if shape else 0
    be = ">f4" if tag == "FLOAT" else ">f8"
    a = np.frombuffer(stream.read(width * count), dtype=be).astype(
        np.float32 if tag == "FLOAT" else np.float64)
    return a.reshape(shape, order="f" if order == "f" else "c")


# -- configuration.json -> config DSL ----------------------------------------

def _act(name):
    return (name or "identity").lower()


def _loss_name(layer_json):
    ln = layer_json.get("lossFn") or layer_json.get("lossFunction")
    if isinstance(ln, dict):  # ILossFunction object form {"@class": ...}
        cls = ln.get("@class", "")
        mapping = {
            "LossMCXENT": "mcxent", "LossMSE": "mse",
            "LossBinaryXENT": "xent", "LossNegativeLogLikelihood":
            "negativeloglikelihood", "LossL2": "l2", "LossL1": "l1",
            "LossKLD": "kl_divergence", "LossCosineProximity":
            "cosine_proximity", "LossHinge": "hinge",
            "LossSquaredHinge": "squared_hinge", "LossPoisson": "poisson",
            "LossMAE": "mean_absolute_error",
        }
        for key, val in mapping.items():
            if key in cls:
                return val
        raise ValueError(f"unmapped DL4J loss class {cls!r}")
    return (ln or "mcxent").lower()


def _map_layer(name: str, lj: dict):
    """One DL4J layer-conf JSON object -> (this framework's config, DL4J
    type tag). Covers the importable parameterized layer set."""
    act = _act(lj.get("activationFn") or lj.get("activation"))
    n_in = int(lj.get("nin") or lj.get("nIn") or 0)
    n_out = int(lj.get("nout") or lj.get("nOut") or 0)
    common = dict(n_in=n_in or None, n_out=n_out or None, activation=act)
    if name == "dense":
        return L.DenseLayer(**common)
    if name == "output":
        return L.OutputLayer(loss=_loss_name(lj), **common)
    if name == "rnnoutput":
        return L.RnnOutputLayer(loss=_loss_name(lj), **common)
    if name == "convolution":
        return L.ConvolutionLayer(
            kernel_size=tuple(lj.get("kernelSize", (3, 3))),
            stride=tuple(lj.get("stride", (1, 1))),
            padding=tuple(lj.get("padding", (0, 0))),
            convolution_mode="same"
            if lj.get("convolutionMode") == "Same" else "truncate",
            **common,
        )
    if name == "subsampling":
        pt = (lj.get("poolingType") or "MAX").lower()
        return L.SubsamplingLayer(
            pooling_type=pt,
            kernel_size=tuple(lj.get("kernelSize", (2, 2))),
            stride=tuple(lj.get("stride", (2, 2))),
            padding=tuple(lj.get("padding", (0, 0))),
            convolution_mode="same"
            if lj.get("convolutionMode") == "Same" else "truncate",
        )
    if name == "batchNormalization":
        return L.BatchNormalization(
            n_in=n_in or None, eps=lj.get("eps", 1e-5),
            decay=lj.get("decay", 0.9),
            lock_gamma_beta=bool(lj.get("lockGammaBeta", False)),
            gamma=lj.get("gamma", 1.0), beta=lj.get("beta", 0.0),
        )
    if name in ("LSTM", "gravesLSTM"):
        cls = L.LSTM if name == "LSTM" else L.GravesLSTM
        return cls(
            forget_gate_bias_init=lj.get("forgetGateBiasInit", 1.0),
            gate_activation=_act(lj.get("gateActivationFn", "sigmoid")),
            **common,
        )
    if name == "embedding":
        return L.EmbeddingLayer(**common)
    if name == "activation":
        return L.ActivationLayer(activation=act)
    if name == "dropout":
        return L.DropoutLayer(dropout=lj.get("dropOut", 0.5))
    if name == "globalPooling":
        return L.GlobalPoolingLayer(
            pooling_type=(lj.get("poolingType") or "MAX").lower())
    raise ValueError(f"unsupported DL4J layer type {name!r} for import")


def _perm_ifog(cols: np.ndarray, H: int) -> np.ndarray:
    """Columns [I,F,O,G] (DL4J: I=candidate, G=input gate,
    LSTMHelpers.java:64) -> this framework's [i(gate), f, g(candidate),
    o]: take DL4J blocks [G, F, I, O]."""
    I, F, O, G = (cols[..., i * H:(i + 1) * H] for i in range(4))
    return np.concatenate([G, F, I, O], axis=-1)


# -- shared flat-buffer walk -------------------------------------------------

def _consume_layer_params(take, tag: str, lc, p: dict, lj: dict, state):
    """Consume one layer's slice of the DL4J flat buffer into this
    framework's param dict `p` (and BN running stats into `state`).
    Layouts per nn/params/* (module docstring). Returns the state dict
    (possibly replaced) for the caller to store back."""
    if tag in ("dense", "output", "rnnoutput", "embedding"):
        n_in, n_out = int(lc.n_in), int(lc.n_out)
        W = take(n_in * n_out).reshape((n_in, n_out), order="F")
        b = take(n_out)
        p["W"] = p["W"].at[:].set(W)
        p["b"] = p["b"].at[:].set(b)
    elif tag == "convolution":
        kh, kw = (int(k) for k in lc.kernel_size)
        n_in, n_out = int(lc.n_in), int(lc.n_out)
        W = take(n_out * n_in * kh * kw).reshape(
            (n_out, n_in, kh, kw), order="F")
        p["W"] = p["W"].at[:].set(W.transpose(2, 3, 1, 0))  # -> HWIO
        p["b"] = p["b"].at[:].set(take(n_out))
    elif tag == "batchNormalization":
        n = int(lc.n_in)
        if lj.get("lockGammaBeta", False):
            # BatchNormalizationParamInitializer stores only mean/var when
            # gamma/beta are locked; the fixed values come from the conf
            p["gamma"] = p["gamma"].at[:].set(
                np.full(n, lj.get("gamma", 1.0), np.float32))
            p["beta"] = p["beta"].at[:].set(
                np.full(n, lj.get("beta", 0.0), np.float32))
        else:
            p["gamma"] = p["gamma"].at[:].set(take(n))
            p["beta"] = p["beta"].at[:].set(take(n))
        mean, var = take(n), take(n)
        st = dict(state or {})
        st["mean"] = st["mean"].at[:].set(mean)
        st["var"] = st["var"].at[:].set(var)
        return st
    elif tag in ("LSTM", "gravesLSTM"):
        n_in, H = int(lc.n_in), int(lc.n_out)
        W = take(n_in * 4 * H).reshape((n_in, 4 * H), order="F")
        rw_cols = 4 * H + (3 if tag == "gravesLSTM" else 0)
        RW_full = take(H * rw_cols).reshape((H, rw_cols), order="F")
        b = take(4 * H)
        p["W"] = p["W"].at[:].set(_perm_ifog(W, H))
        p["RW"] = p["RW"].at[:].set(_perm_ifog(RW_full[:, :4 * H], H))
        p["b"] = p["b"].at[:].set(_perm_ifog(b[None, :], H)[0])
        if tag == "gravesLSTM":
            # peephole columns [wFF, wOO, wGG] (LSTMHelpers.java:104)
            p["pF"] = p["pF"].at[:].set(RW_full[:, 4 * H])
            p["pO"] = p["pO"].at[:].set(RW_full[:, 4 * H + 1])
            p["pI"] = p["pI"].at[:].set(RW_full[:, 4 * H + 2])
    elif tag in ("activation", "dropout", "subsampling", "globalPooling"):
        pass  # no params
    else:
        raise ValueError(f"no flat layout for layer tag {tag!r}")
    return state


# -- updater-state flat layout (updaterState.bin) -----------------------------

# nd4j GradientUpdater state components, in view order: two-component
# updaters split their block view in halves (AdamUpdater m|v), single use
# the whole view. sgd/none have stateSize 0 (no updaterState.bin written).
_UPDATER_COMPONENTS = {
    "adam": ("m", "v"), "adamax": ("m", "u"), "adadelta": ("msg", "msdx"),
    "nesterovs": ("v",), "adagrad": ("h",), "rmsprop": ("r",),
    "sgd": (), "none": (),
}


def _state_entries(lc):
    """DL4J flat-order updater-state entries for one layer conf: a list of
    dicts {size, to(comp_arrays)->flat, frm(flat)->{fw_name: array}, cfg}
    where cfg is "param"/"bias" (updater-carrying, effective-lr keyed) or
    "none" (DL4J params with updater NONE — BN running mean/var — that
    carry no state but break block contiguity). The to/frm transforms are
    the SAME layout maps the coefficients walk uses (f-order reshapes,
    HWIO<->OIHW transpose, [I,F,O,G]<->[i,f,g,o] gate permutation):
    moment arrays live in their param's layout."""
    inner = lc.inner if isinstance(lc, L.FrozenLayer) else lc
    entries = []
    if isinstance(inner, (L.DenseLayer, L.OutputLayer, L.RnnOutputLayer,
                          L.EmbeddingLayer)):
        n_in, n_out = int(inner.n_in), int(inner.n_out)
        entries.append(dict(
            size=n_in * n_out,
            to=lambda c: c["W"].reshape(-1, order="F"),
            frm=lambda v: {"W": v.reshape((n_in, n_out), order="F")},
            cfg="param"))
        entries.append(dict(
            size=n_out,
            to=lambda c: c["b"].reshape(-1),
            frm=lambda v: {"b": v},
            cfg="bias"))
    elif isinstance(inner, L.ConvolutionLayer):
        kh, kw = (int(k) for k in inner.kernel_size)
        n_in, n_out = int(inner.n_in), int(inner.n_out)
        entries.append(dict(
            size=n_out * n_in * kh * kw,
            to=lambda c: c["W"].transpose(3, 2, 0, 1).reshape(-1, order="F"),
            frm=lambda v: {"W": v.reshape((n_out, n_in, kh, kw),
                                          order="F").transpose(2, 3, 1, 0)},
            cfg="param"))
        entries.append(dict(
            size=n_out, to=lambda c: c["b"].reshape(-1),
            frm=lambda v: {"b": v}, cfg="bias"))
    elif isinstance(inner, L.BatchNormalization):
        n = int(inner.n_in)
        if not inner.lock_gamma_beta:
            entries.append(dict(size=n, to=lambda c: c["gamma"].reshape(-1),
                                frm=lambda v: {"gamma": v}, cfg="param"))
            entries.append(dict(size=n, to=lambda c: c["beta"].reshape(-1),
                                frm=lambda v: {"beta": v}, cfg="param"))
        # running mean/var: DL4J params with updater NONE (stateSize 0)
        entries.append(dict(size=n, to=None, frm=None, cfg="none"))
        entries.append(dict(size=n, to=None, frm=None, cfg="none"))
    elif isinstance(inner, (L.LSTM, L.GravesLSTM)):
        graves = isinstance(inner, L.GravesLSTM)
        n_in, H = int(inner.n_in), int(inner.n_out)

        def inv(cols):
            return np.concatenate(
                [cols[..., 2 * H:3 * H], cols[..., H:2 * H],
                 cols[..., 3 * H:], cols[..., :H]], axis=-1)

        entries.append(dict(
            size=n_in * 4 * H,
            to=lambda c: inv(c["W"]).reshape(-1, order="F"),
            frm=lambda v: {"W": _perm_ifog(
                v.reshape((n_in, 4 * H), order="F"), H)},
            cfg="param"))
        rw_cols = 4 * H + (3 if graves else 0)

        def rw_to(c):
            RW = inv(c["RW"])
            if graves:
                RW = np.concatenate(
                    [RW, c["pF"][:, None], c["pO"][:, None],
                     c["pI"][:, None]], axis=1)
            return RW.reshape(-1, order="F")

        def rw_frm(v):
            RW_full = v.reshape((H, rw_cols), order="F")
            out = {"RW": _perm_ifog(RW_full[:, :4 * H], H)}
            if graves:
                out["pF"] = RW_full[:, 4 * H]
                out["pO"] = RW_full[:, 4 * H + 1]
                out["pI"] = RW_full[:, 4 * H + 2]
            return out

        entries.append(dict(size=H * rw_cols, to=rw_to, frm=rw_frm,
                            cfg="param"))
        entries.append(dict(
            size=4 * H,
            to=lambda c: inv(c["b"][None, :])[0],
            frm=lambda v: {"b": _perm_ifog(v[None, :], H)[0]},
            cfg="bias"))
    elif isinstance(inner, (L.ActivationLayer, L.DropoutLayer,
                            L.SubsamplingLayer, L.GlobalPoolingLayer)):
        pass  # no params, no state
    else:
        raise ValueError(
            f"no updater-state layout for layer {type(inner).__name__}")
    return entries


def _effective_lr(net_conf, lc, kind):
    """Mirrors NetworkBase._lr_mult_tree: per-layer learning_rate and
    bias_learning_rate overrides decide UpdaterBlock splits (UpdaterUtils
    updaterConfigurationsEqual compares lr)."""
    inner = lc.inner if isinstance(lc, L.FrozenLayer) else lc
    if kind == "bias" and getattr(inner, "bias_learning_rate", None) is not None:
        return inner.bias_learning_rate
    if getattr(inner, "learning_rate", None) is not None:
        return inner.learning_rate
    return net_conf.learning_rate


def _updater_blocks(net_conf, indexed_layer_confs):
    """Group (state_idx, entry) pairs into contiguous UpdaterBlocks the
    way BaseMultiLayerUpdater does (:63-104): a new block starts whenever
    the effective updater configuration changes (including the NONE
    pseudo-config of BN mean/var). Input: [(state_idx, layer_conf)] in
    the flat-walk order."""
    upd = net_conf.updater.lower()
    blocks, cur_key, cur = [], None, []
    for i, lc in indexed_layer_confs:
        for e in _state_entries(lc):
            key = (("none",) if e["cfg"] == "none"
                   else (upd, _effective_lr(net_conf, lc, e["cfg"])))
            if key != cur_key:
                if cur:
                    blocks.append((cur_key, cur))
                cur_key, cur = key, []
            cur.append((i, e))
    if cur:
        blocks.append((cur_key, cur))
    return blocks


def updater_state_to_flat(net, indexed_layer_confs=None) -> np.ndarray:
    """The network's updater state in the reference's state-view layout
    (what Nd4j.write(updaterState, ...) serializes)."""
    comps = _UPDATER_COMPONENTS.get(net.updater_def.name, ())
    pairs = (indexed_layer_confs if indexed_layer_confs is not None
             else list(enumerate(net.layer_confs)))
    parts = []
    for key, entries in _updater_blocks(net.net_conf, pairs):
        if key[0] == "none" or not comps:
            continue
        for comp in comps:
            for i, e in entries:
                st = net.upd_state[i]
                c = {name: np.asarray(leaf[comp])
                     for name, leaf in st.items()
                     if isinstance(leaf, dict) and comp in leaf}
                parts.append(np.asarray(e["to"](c), np.float32).reshape(-1))
    return (np.concatenate(parts) if parts
            else np.zeros(0, np.float32))


def restore_updater_state(net, flat: np.ndarray,
                          indexed_layer_confs=None) -> None:
    """Inverse of updater_state_to_flat: load a reference state view into
    the network's per-leaf updater state (resume-training parity)."""
    import jax.numpy as jnp

    comps = _UPDATER_COMPONENTS.get(net.updater_def.name, ())
    flat = np.asarray(flat).reshape(-1)
    if not comps:
        if flat.size:
            raise ValueError(
                f"updater {net.updater_def.name!r} is stateless but "
                f"updaterState.bin holds {flat.size} values")
        return
    pairs = (indexed_layer_confs if indexed_layer_confs is not None
             else list(enumerate(net.layer_confs)))
    blocks = _updater_blocks(net.net_conf, pairs)
    # validate BEFORE mutating: a wrong-sized view must not leave a
    # half-restored (corrupted old/new mix) updater state behind
    expected = sum(
        len(comps) * sum(e["size"] for _, e in entries)
        for key, entries in blocks if key[0] != "none")
    if expected != flat.size:
        raise ValueError(
            f"updaterState.bin length mismatch: layout expects {expected} "
            f"values, file holds {flat.size}")
    off = 0
    for key, entries in blocks:
        if key[0] == "none":
            continue
        for comp in comps:
            for i, e in entries:
                vec = flat[off:off + e["size"]]
                off += e["size"]
                for name, arr in e["frm"](vec).items():
                    cur = net.upd_state[i][name][comp]
                    net.upd_state[i][name][comp] = jnp.asarray(
                        arr, cur.dtype).reshape(cur.shape)


def _training_builder(confs: List[dict], bodies: List[dict],
                      precision: str):
    """Network builder with the training hyperparameters a DL4J zip
    carries restored (0.8.x serializes updater/learningRate and the
    updater's own hyperparameters per LAYER body; iterationCount sits on
    the per-layer NeuralNetConfiguration wrapper). Without these, a
    migrated model would resume with default sgd and the imported
    optimizer moments would be meaningless."""
    nc0 = confs[0] if confs else {}
    b0 = bodies[0] if bodies else {}
    get = lambda key, default=None: b0.get(key, nc0.get(key, default))
    builder = NeuralNetConfiguration.builder().precision(precision)
    updater = get("updater")
    if updater:
        builder = builder.updater(str(updater).lower())
    lr = get("learningRate")
    if lr is not None:
        builder = builder.learning_rate(float(lr))
    for json_key, method in (
        ("momentum", "momentum"), ("rho", "rho"),
        ("rmsDecay", "rms_decay"), ("adamMeanDecay", "adam_mean_decay"),
        ("adamVarDecay", "adam_var_decay"), ("epsilon", "epsilon"),
    ):
        v = get(json_key)
        if v is not None:
            builder = getattr(builder, method)(float(v))
    return builder


# -- the importer ------------------------------------------------------------

def import_dl4j_multilayer(path: str, precision: str = "f32",
                           load_updater: bool = True):
    """Load a reference-format model zip into a MultiLayerNetwork.

    Returns the network with parameters, BN running stats, the updater
    state (optimizer moments from updaterState.bin, when present and
    load_updater — mirroring restoreMultiLayerNetwork(file, loadUpdater),
    ModelSerializer.java:148) and the iteration counter restored, so a
    migrated model RESUMES training rather than restarting its
    moments."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path) as zf:
        conf_json = json.loads(zf.read("configuration.json"))
        flat = read_nd4j_array(io.BytesIO(zf.read("coefficients.bin")))
        upd_flat = None
        if load_updater and "updaterState.bin" in zf.namelist():
            upd_flat = read_nd4j_array(io.BytesIO(zf.read("updaterState.bin")))
    flat = np.asarray(flat).reshape(-1)

    confs = conf_json.get("confs", [])
    layers: List = []
    tags: List[str] = []
    bodies: List[dict] = []
    for c in confs:
        lj = c.get("layer", {})
        if not lj:
            raise ValueError("conf without layer entry")
        (tag, body), = lj.items()
        layers.append(_map_layer(tag, body))
        tags.append(tag)
        bodies.append(body)

    builder = _training_builder(confs, bodies, precision).list()
    iteration = int((confs[0] if confs else {}).get("iterationCount", 0))
    for l in layers:
        builder = builder.layer(l)
    # input type from the first layer's nIn (feed-forward/recurrent import;
    # CNN zips additionally carry inputPreProcessors, mapped coarsely here)
    first = layers[0]
    if isinstance(first, (L.LSTM, L.GravesLSTM)):
        builder = builder.set_input_type(InputType.recurrent(first.n_in))
    else:
        builder = builder.set_input_type(InputType.feed_forward(first.n_in))
    net = MultiLayerNetwork(builder.build()).init()

    # walk the flat buffer in layer order, mirroring nn/params layouts
    off = 0

    def take(n):
        nonlocal off
        out = flat[off:off + n]
        if out.size != n:
            raise ValueError(
                f"coefficients.bin too short: wanted {n} at offset {off}, "
                f"have {flat.size}")
        off += n
        return out

    for i, (tag, lc, lj) in enumerate(zip(tags, layers, bodies)):
        net.state_list[i] = _consume_layer_params(
            take, tag, lc, net.params_list[i], lj, net.state_list[i])
    if off != flat.size:
        raise ValueError(
            f"coefficients.bin length mismatch: consumed {off} of {flat.size}")
    net.iteration = iteration
    if upd_flat is not None:
        restore_updater_state(net, np.asarray(upd_flat).reshape(-1))
    # free pre-flight: shapeflow over the translated configuration — a
    # mistranslated zip is diagnosed at import (logged findings, also on
    # net.import_preflight), not five layers deep at trace time
    from deeplearning4j_tpu.analysis import preflight_report

    net.import_preflight = preflight_report(net.conf, origin=path)
    return net


# -- fixture/export writer ---------------------------------------------------

def _export_layer(lc, p: dict, st) -> Tuple[str, dict, List[np.ndarray]]:
    """One layer conf + params (+ BN state) -> (DL4J tag, layer-conf JSON
    body, flat parts in the reference layouts)."""
    flat_parts: List[np.ndarray] = []
    if isinstance(lc, L.ConvolutionLayer):
        tag = "convolution"
        body = {
            "nin": int(lc.n_in), "nout": int(lc.n_out),
            "activationFn": lc.activation,
            "kernelSize": list(lc.kernel_size),
            "stride": list(lc.stride), "padding": list(lc.padding),
            "convolutionMode":
                "Same" if str(lc.convolution_mode).endswith("same")
                else "Truncate",
        }
        W = p["W"].transpose(3, 2, 0, 1)  # HWIO -> [nOut,nIn,kh,kw]
        flat_parts += [W.reshape(-1, order="F"), p["b"].reshape(-1)]
    elif isinstance(lc, L.BatchNormalization):
        tag = "batchNormalization"
        body = {"nin": int(lc.n_in), "nout": int(lc.n_in),
                "eps": lc.eps, "decay": lc.decay}
        st = st or {}
        if lc.lock_gamma_beta:
            body["lockGammaBeta"] = True
            body["gamma"], body["beta"] = lc.gamma, lc.beta
        else:
            flat_parts += [p["gamma"], p["beta"]]
        flat_parts += [np.asarray(st.get("mean")), np.asarray(st.get("var"))]
    elif isinstance(lc, (L.LSTM, L.GravesLSTM)):
        graves = isinstance(lc, L.GravesLSTM)
        tag = "gravesLSTM" if graves else "LSTM"
        H = int(lc.n_out)
        body = {"nin": int(lc.n_in), "nout": H,
                "activationFn": lc.activation,
                "gateActivationFn": lc.gate_activation,
                "forgetGateBiasInit": lc.forget_gate_bias_init}
        inv = lambda cols: np.concatenate(
            [cols[..., 2 * H:3 * H],           # I <- my g (candidate)
             cols[..., H:2 * H],               # F <- my f
             cols[..., 3 * H:],                # O <- my o
             cols[..., :H]], axis=-1)          # G <- my i (input gate)
        RW = inv(p["RW"])
        if graves:
            RW = np.concatenate(
                [RW, p["pF"][:, None], p["pO"][:, None],
                 p["pI"][:, None]], axis=1)
        flat_parts += [inv(p["W"]).reshape(-1, order="F"),
                       RW.reshape(-1, order="F"),
                       inv(p["b"][None, :])[0]]
    elif isinstance(lc, L.OutputLayer):
        tag = "output"
        body = {"nin": int(lc.n_in), "nout": int(lc.n_out),
                "activationFn": lc.activation, "lossFn": lc.loss}
        flat_parts += [p["W"].reshape(-1, order="F"), p["b"].reshape(-1)]
    elif isinstance(lc, L.RnnOutputLayer):
        tag = "rnnoutput"
        body = {"nin": int(lc.n_in), "nout": int(lc.n_out),
                "activationFn": lc.activation, "lossFn": lc.loss}
        flat_parts += [p["W"].reshape(-1, order="F"), p["b"].reshape(-1)]
    elif isinstance(lc, L.DenseLayer):
        tag = "dense"
        body = {"nin": int(lc.n_in), "nout": int(lc.n_out),
                "activationFn": lc.activation}
        flat_parts += [p["W"].reshape(-1, order="F"), p["b"].reshape(-1)]
    elif isinstance(lc, L.EmbeddingLayer):
        tag = "embedding"
        body = {"nin": int(lc.n_in), "nout": int(lc.n_out),
                "activationFn": lc.activation}
        flat_parts += [p["W"].reshape(-1, order="F"), p["b"].reshape(-1)]
    elif isinstance(lc, L.ActivationLayer):
        tag, body = "activation", {"activationFn": lc.activation}
    elif isinstance(lc, L.SubsamplingLayer):
        tag = "subsampling"
        body = {"poolingType": str(lc.pooling_type).upper(),
                "kernelSize": list(lc.kernel_size),
                "stride": list(lc.stride), "padding": list(lc.padding),
                "convolutionMode":
                    "Same" if str(lc.convolution_mode).endswith("same")
                    else "Truncate"}
    elif isinstance(lc, L.GlobalPoolingLayer):
        tag = "globalPooling"
        body = {"poolingType": str(lc.pooling_type).upper()}
    elif isinstance(lc, L.DropoutLayer):
        tag, body = "dropout", {"dropOut": lc.dropout}
    else:
        raise ValueError(f"cannot export layer {type(lc).__name__}")
    return tag, body, flat_parts


def _conf_training_json(net) -> dict:
    """Per-layer-body training hyperparameters, reference style."""
    nc = net.net_conf
    out = {"updater": nc.updater.upper(), "learningRate": nc.learning_rate}
    per_updater = {
        "nesterovs": {"momentum": nc.momentum},
        "adam": {"adamMeanDecay": nc.adam_mean_decay,
                 "adamVarDecay": nc.adam_var_decay, "epsilon": nc.epsilon},
        "adamax": {"adamMeanDecay": nc.adam_mean_decay,
                   "adamVarDecay": nc.adam_var_decay, "epsilon": nc.epsilon},
        "adadelta": {"rho": nc.rho, "epsilon": nc.epsilon},
        "rmsprop": {"rmsDecay": nc.rms_decay, "epsilon": nc.epsilon},
        "adagrad": {"epsilon": nc.epsilon},
    }
    out.update(per_updater.get(nc.updater.lower(), {}))
    return out


def export_dl4j_zip(net, path: str, save_updater: bool = True) -> None:
    """Write a network in the reference zip format (the inverse mapping of
    import_dl4j_multilayer — used for fixtures and for handing models back
    to reference-era tooling). Only layer types listed above. With
    save_updater (the reference's writeModel saveUpdater flag), the
    optimizer state view goes to updaterState.bin and the per-conf
    iterationCount is emitted, so import->resume matches uninterrupted
    training."""
    train_json = _conf_training_json(net)
    conf_out = {"confs": []}
    flat_parts: List[np.ndarray] = []
    for i, lc in enumerate(net.layer_confs):
        p = {k: np.asarray(v) for k, v in net.params_list[i].items()}
        tag, body, parts = _export_layer(lc, p, net.state_list[i])
        body = {**body, **train_json}
        conf_out["confs"].append({"layer": {tag: body},
                                  "iterationCount": int(net.iteration)})
        flat_parts += parts

    flat = (np.concatenate([f.astype(np.float32).reshape(-1)
                            for f in flat_parts])
            if flat_parts else np.zeros(0, np.float32))
    buf = io.BytesIO()
    write_nd4j_array(flat, buf)
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("configuration.json", json.dumps(conf_out))
        zf.writestr("coefficients.bin", buf.getvalue())
        if save_updater:
            upd = updater_state_to_flat(net)
            if upd.size:
                ubuf = io.BytesIO()
                write_nd4j_array(upd, ubuf)
                zf.writestr("updaterState.bin", ubuf.getvalue())


# -- ComputationGraph zips ----------------------------------------------------
# Reference format (ModelSerializer.java:228 restoreComputationGraph): the
# same zip layout, but configuration.json is a ComputationGraphConfiguration
# — networkInputs / networkOutputs / vertices (LinkedHashMap, JSON order =
# builder order) / vertexInputs — and coefficients.bin concatenates each
# parameterized vertex's flat view in TOPOLOGICAL order
# (ComputationGraph.java:365-402: vertex numbers are inputs-then-JSON-order;
# the flat walk follows topologicalSortOrder(), Kahn's algorithm with a FIFO
# queue whose ties resolve in ascending vertex number — Java HashMap/HashSet
# over small int keys iterate ascending).

def _dl4j_topo_names(inputs: List[str], vertex_names: List[str],
                     vertex_inputs: dict) -> List[str]:
    """The reference's exact topological ordering over vertex NAMES."""
    names = list(inputs) + list(vertex_names)
    idx = {n: i for i, n in enumerate(names)}
    indeg = {i: 0 for i in range(len(names))}
    outs = {i: set() for i in range(len(names))}
    for name, ins in vertex_inputs.items():
        j = idx[name]
        for src in ins:
            outs[idx[src]].add(j)
            indeg[j] += 1
    queue = [i for i in sorted(indeg) if indeg[i] == 0]
    order: List[int] = []
    while queue:
        nxt = queue.pop(0)
        order.append(nxt)
        for j in sorted(outs[nxt]):  # ascending, like HashSet<int> iteration
            indeg[j] -= 1
            if indeg[j] == 0:
                queue.append(j)
    if len(order) != len(names):
        raise ValueError("cycle in imported graph configuration")
    return [names[i] for i in order]


def _map_vertex(tag: str, body: dict):
    """DL4J graph-vertex JSON -> this framework's vertex conf (non-layer
    types; LayerVertex is handled by the importer)."""
    from deeplearning4j_tpu.nn.conf import graph as G

    if tag == "MergeVertex":
        return G.MergeVertex()
    if tag == "ElementWiseVertex":
        return G.ElementWiseVertex(op=str(body.get("op", "Add")).lower())
    if tag == "SubsetVertex":
        return G.SubsetVertex(from_=int(body["from"]), to=int(body["to"]))
    if tag == "StackVertex":
        return G.StackVertex()
    if tag == "UnstackVertex":
        return G.UnstackVertex(from_=int(body["from"]),
                               stack_size=int(body["stackSize"]))
    if tag == "ScaleVertex":
        return G.ScaleVertex(scale=float(body["scaleFactor"]))
    if tag == "ShiftVertex":
        return G.ShiftVertex(shift=float(body.get("shiftFactor", 0.0)))
    if tag == "L2Vertex":
        return G.L2Vertex()
    if tag == "L2NormalizeVertex":
        return G.L2NormalizeVertex()
    if tag == "LastTimeStepVertex":
        return G.LastTimeStepVertex(mask_input=body.get("maskArrayInputName"))
    if tag == "DuplicateToTimeSeriesVertex":
        return G.DuplicateToTimeSeriesVertex(ref_input=body.get("inputName"))
    raise ValueError(f"unsupported DL4J graph vertex type {tag!r} for import")


def import_dl4j_computation_graph(path: str, precision: str = "f32",
                                  load_updater: bool = True):
    """Load a reference-format ComputationGraph zip
    (ModelSerializer.java:228 restoreComputationGraph) into a
    ComputationGraph with parameters, BN stats and (load_updater) the
    optimizer moments + iteration counter restored."""
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import graph as G

    with zipfile.ZipFile(path) as zf:
        cj = json.loads(zf.read("configuration.json"))
        flat = read_nd4j_array(io.BytesIO(zf.read("coefficients.bin")))
        upd_flat = None
        if load_updater and "updaterState.bin" in zf.namelist():
            upd_flat = read_nd4j_array(io.BytesIO(zf.read("updaterState.bin")))
    flat = np.asarray(flat).reshape(-1)

    inputs = list(cj["networkInputs"])
    outputs = list(cj["networkOutputs"])
    vertices_json = cj.get("vertices", {})  # JSON order == builder order
    vertex_inputs = {k: list(v) for k, v in cj.get("vertexInputs", {}).items()}

    layer_confs = {}   # name -> (tag, our layer conf, raw body)
    vertex_confs = {}  # name -> our vertex conf
    for name, vj in vertices_json.items():
        (vtag, vbody), = vj.items()
        if vtag == "LayerVertex":
            lj = vbody.get("layerConf", {}).get("layer", {})
            if not lj:
                raise ValueError(f"LayerVertex {name!r} without layer conf")
            (ltag, lbody), = lj.items()
            layer_confs[name] = (ltag, _map_layer(ltag, lbody), lbody)
        else:
            vertex_confs[name] = _map_vertex(vtag, vbody)

    topo = _dl4j_topo_names(inputs, list(vertices_json), vertex_inputs)

    lbodies = [layer_confs[n][2] for n in topo if n in layer_confs]
    builder = (_training_builder(
        [cj.get("defaultConfiguration", cj)], lbodies, precision)
        .graph_builder().add_inputs(*inputs))
    iteration = int(cj.get("iterationCount",
                           cj.get("defaultConfiguration", {})
                           .get("iterationCount", 0)))
    for name in topo:  # topo order satisfies inputs-before-use
        if name in inputs:
            continue
        ins = vertex_inputs[name]
        if name in layer_confs:
            builder.add_layer(name, layer_confs[name][1], *ins)
        else:
            builder.add_vertex(name, vertex_confs[name], *ins)
    builder.set_outputs(*outputs)
    net = ComputationGraph(builder.build()).init()

    off = 0

    def take(n):
        nonlocal off
        out = flat[off:off + n]
        if out.size != n:
            raise ValueError(
                f"coefficients.bin too short: wanted {n} at offset {off}, "
                f"have {flat.size}")
        off += n
        return out

    # flat walk in the REFERENCE topo order, but params land by name in
    # this framework's own ordering (net._pidx maps names to param slots)
    for name in topo:
        if name not in layer_confs:
            continue
        tag, lc, lbody = layer_confs[name]
        i = net._pidx[name]
        net.state_list[i] = _consume_layer_params(
            take, tag, lc, net.params_list[i], lbody, net.state_list[i])
    if off != flat.size:
        raise ValueError(
            f"coefficients.bin length mismatch: consumed {off} of {flat.size}")
    net.iteration = iteration
    if upd_flat is not None:
        pairs = [(net._pidx[n], layer_confs[n][1])
                 for n in topo if n in layer_confs]
        restore_updater_state(net, np.asarray(upd_flat).reshape(-1),
                              indexed_layer_confs=pairs)
    from deeplearning4j_tpu.analysis import preflight_report

    net.import_preflight = preflight_report(net.conf, origin=path)
    return net


def export_dl4j_graph(net, path: str, save_updater: bool = True) -> None:
    """Write a ComputationGraph in the reference zip format (the inverse of
    import_dl4j_computation_graph — fixtures + hand-back interop), with
    updaterState.bin + iterationCount when save_updater."""
    from deeplearning4j_tpu.nn.conf import graph as G

    conf = net.conf
    train_json = _conf_training_json(net)
    vertices_json = {}
    vertex_inputs = {}
    for name, v in conf.vertices.items():
        vertex_inputs[name] = list(conf.vertex_inputs[name])
        if isinstance(v, G.LayerVertex):
            # params are exported in the flat walk below; here only the conf
            ltag, lbody, _ = _export_layer_conf_only(v.layer)
            vertices_json[name] = {
                "LayerVertex": {"layerConf": {
                    "layer": {ltag: {**lbody, **train_json}}}}}
        else:
            vertices_json[name] = _vertex_to_json(v)

    topo = _dl4j_topo_names(conf.inputs, list(conf.vertices),
                            vertex_inputs)
    flat_parts: List[np.ndarray] = []
    for name in topo:
        v = conf.vertices.get(name)
        if not isinstance(v, G.LayerVertex):
            continue
        i = net._pidx[name]
        p = {k: np.asarray(val) for k, val in net.params_list[i].items()}
        _, _, parts = _export_layer(v.layer, p, net.state_list[i])
        flat_parts += parts

    conf_out = {
        "networkInputs": list(conf.inputs),
        "networkOutputs": list(conf.outputs),
        "vertices": vertices_json,
        "vertexInputs": vertex_inputs,
        "iterationCount": int(net.iteration),
    }
    flat = (np.concatenate([f.astype(np.float32).reshape(-1)
                            for f in flat_parts])
            if flat_parts else np.zeros(0, np.float32))
    buf = io.BytesIO()
    write_nd4j_array(flat, buf)
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("configuration.json", json.dumps(conf_out))
        zf.writestr("coefficients.bin", buf.getvalue())
        if save_updater:
            pairs = [(net._pidx[n], conf.vertices[n].layer)
                     for n in topo
                     if isinstance(conf.vertices.get(n), G.LayerVertex)]
            upd = updater_state_to_flat(net, indexed_layer_confs=pairs)
            if upd.size:
                ubuf = io.BytesIO()
                write_nd4j_array(upd, ubuf)
                zf.writestr("updaterState.bin", ubuf.getvalue())


def _export_layer_conf_only(lc) -> Tuple[str, dict, list]:
    """Layer conf -> (tag, JSON body): run _export_layer over throwaway
    correctly-shaped params so the body logic stays in one place."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.layers.registry import (
        init_layer_params,
        init_layer_state,
    )

    p = {k: np.asarray(v) for k, v in init_layer_params(
        jax.random.PRNGKey(0), lc, jnp.float32).items()}
    st = init_layer_state(lc, jnp.float32)
    tag, body, _ = _export_layer(lc, p, st)
    return tag, body, []


def _vertex_to_json(v) -> dict:
    from deeplearning4j_tpu.nn.conf import graph as G

    if isinstance(v, G.MergeVertex):
        return {"MergeVertex": {}}
    if isinstance(v, G.ElementWiseVertex):
        return {"ElementWiseVertex": {"op": v.op.capitalize()}}
    if isinstance(v, G.SubsetVertex):
        return {"SubsetVertex": {"from": v.from_, "to": v.to}}
    if isinstance(v, G.StackVertex):
        return {"StackVertex": {}}
    if isinstance(v, G.UnstackVertex):
        return {"UnstackVertex": {"from": v.from_, "stackSize": v.stack_size}}
    if isinstance(v, G.ScaleVertex):
        return {"ScaleVertex": {"scaleFactor": v.scale}}
    if isinstance(v, G.ShiftVertex):
        return {"ShiftVertex": {"shiftFactor": v.shift}}
    if isinstance(v, G.L2Vertex):
        return {"L2Vertex": {}}
    if isinstance(v, G.L2NormalizeVertex):
        return {"L2NormalizeVertex": {}}
    if isinstance(v, G.LastTimeStepVertex):
        return {"LastTimeStepVertex": {"maskArrayInputName": v.mask_input}}
    if isinstance(v, G.DuplicateToTimeSeriesVertex):
        return {"DuplicateToTimeSeriesVertex": {"inputName": v.ref_input}}
    raise ValueError(f"cannot export vertex {type(v).__name__}")
