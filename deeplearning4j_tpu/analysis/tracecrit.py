"""Span-tree reconstruction + critical-path analysis over trace exports
(`cli trace <file-or-url>` — the readout half of utils/tracing's
distributed tracer).

Input: the JSONL span export (utils/tracing.Tracer.to_jsonl — one event
per line with `trace`/`id`/`parent`/`ts`/`dur` in microseconds), from a
file, a `TracingListener` artifact, or a live server's `GET /trace`.

Per trace the analyzer rebuilds the span tree and computes the
**critical path**: starting from the trace's covering root span, walk
backward from the span's end picking the latest-finishing child chain of
non-overlapping intervals — the sequence of spans that actually gated
the end-to-end latency. Each step on the path is charged its SELF time
(duration minus the time covered by its own on-path children), so the
per-stage breakdown sums to ~the root duration and answers "which stage
do I fix to move the p99": the falsifiable counterpart to the admission
estimator's predicted-late decisions, and the resolution target for the
histogram exemplars in utils/metrics (exemplar trace_id -> this report).

Partial traces are handled: a span whose parent id is absent from the
export (the remote half of a cross-process trace, or a parent that aged
out of the ring) is treated as a root — the analysis is honest about
what the export contains rather than refusing it.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

# child intervals jitter by clock granularity; allow this much overhang
# (microseconds) when chaining "non-overlapping" children
_EPS_US = 1.0


def parse_jsonl(text: str) -> List[dict]:
    """Span events from a JSONL export; blank/corrupt lines are skipped
    (a live /trace endpoint can race a writer mid-line)."""
    events = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(ev, dict) and "name" in ev:
            events.append(ev)
    return events


def group_traces(events: List[dict]) -> Dict[str, List[dict]]:
    """{trace_id: [events]} over complete ("X"-phase) spans AND instant
    markers; events without a trace id (pre-distributed exports) are
    dropped — there is no tree to build for them."""
    out: Dict[str, List[dict]] = {}
    for ev in events:
        tid = ev.get("trace")
        if tid:
            out.setdefault(tid, []).append(ev)
    return out


def _spans_of(trace_events: List[dict]) -> List[dict]:
    return [e for e in trace_events if e.get("ph", "X") == "X"]


def _roots(spans: List[dict]) -> List[dict]:
    ids = {s["id"] for s in spans}
    return [s for s in spans
            if s.get("parent") is None or s["parent"] not in ids]


def _union_len(intervals: List[tuple]) -> float:
    total, cur_a, cur_b = 0.0, None, None
    for a, b in sorted(intervals):
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        total += cur_b - cur_a
    return total


def critical_path(trace_events: List[dict]) -> List[dict]:
    """The latency-gating chain of one trace, root first. Each entry:
    {name, id, start_us, dur_us, self_us, args} where `self_us` is the
    span's duration minus the time covered by its own on-path
    descendants — the per-stage charge that sums to ~the root duration.

    Async-aware: a child recorded retroactively after a queue hop (the
    serving pipeline's dispatch span under its request's already-closed
    admission span) can END after its parent — chain selection therefore
    works on each span's *effective* end (its own end or its latest
    descendant's, whichever is later), so the path follows the handoff
    instead of stopping at the first closed parent."""
    spans = _spans_of(trace_events)
    if not spans:
        return []
    children: Dict[object, List[dict]] = {}
    ids = {s["id"] for s in spans}
    for s in spans:
        p = s.get("parent")
        if p is not None and p in ids:
            children.setdefault(p, []).append(s)
    roots = _roots(spans)

    eff_memo: Dict[object, float] = {}

    def eff_end(s: dict) -> float:
        v = eff_memo.get(s["id"])
        if v is None:
            v = s.get("ts", 0.0) + s.get("dur", 0.0)
            eff_memo[s["id"]] = v  # breaks cycles from corrupt exports
            for k in children.get(s["id"], []):
                v = max(v, eff_end(k))
            eff_memo[s["id"]] = v
        return v

    # the covering root: widest effective window, earliest start on ties.
    # A parent-id cycle (corrupt/merged export) can leave NO root — fall
    # back to every span as a candidate rather than refusing the export
    root = max(roots or spans,
               key=lambda s: (eff_end(s) - s.get("ts", 0.0),
                              -s.get("ts", 0.0)))

    path: List[dict] = []
    visited = set()  # parent-id cycles must not recurse forever

    def walk(span: dict):
        if span["id"] in visited:
            return
        visited.add(span["id"])
        path.append(span)
        kids = children.get(span["id"], [])
        # walk backward from the span's effective end choosing the
        # latest-finishing chain of non-overlapping children — the
        # fork-join critical chain; anything not on it ran in the
        # shadow of it
        chain: List[dict] = []
        cursor = eff_end(span) + _EPS_US
        for k in sorted(kids, key=eff_end, reverse=True):
            if eff_end(k) <= cursor:
                chain.append(k)
                cursor = k.get("ts", 0.0) + _EPS_US
        for k in reversed(chain):
            walk(k)

    walk(root)

    # self time: each path step's duration minus the union of its
    # on-path DESCENDANTS' intervals clipped to its own — double-count-
    # free even when async children overhang their parents
    parent_of = {s["id"]: s.get("parent") for s in spans}

    def is_descendant(did, aid) -> bool:
        cur, seen = parent_of.get(did), set()
        while cur is not None and cur not in seen:
            if cur == aid:
                return True
            seen.add(cur)
            cur = parent_of.get(cur)
        return False

    out: List[dict] = []
    for s in path:
        s0 = s.get("ts", 0.0)
        s1 = s0 + s.get("dur", 0.0)
        intervals = []
        for o in path:
            if o is s or not is_descendant(o["id"], s["id"]):
                continue
            a = max(s0, o.get("ts", 0.0))
            b = min(s1, o.get("ts", 0.0) + o.get("dur", 0.0))
            if b > a:
                intervals.append((a, b))
        out.append({
            "name": s.get("name", "?"),
            "id": s["id"],
            "start_us": s0,
            "dur_us": s.get("dur", 0.0),
            "self_us": max(0.0, s.get("dur", 0.0)
                           - _union_len(intervals)),
            "args": s.get("args") or {},
        })
    return out


def analyze_trace(trace_id: str, trace_events: List[dict]) -> dict:
    """One trace's report: covering duration, span count, the critical
    path, and the per-stage (span-name) self-time breakdown."""
    spans = _spans_of(trace_events)
    path = critical_path(trace_events)
    stages: Dict[str, float] = {}
    for step in path:
        stages[step["name"]] = stages.get(step["name"], 0.0) \
            + step["self_us"]
    # covering window, not the root span's own duration: async children
    # recorded after a queue handoff can overhang the root (an
    # admission-rooted trace ends at its forward, not at admission)
    duration = (max(s["start_us"] + s["dur_us"] for s in path)
                - path[0]["start_us"]) if path else 0.0
    return {
        "trace_id": trace_id,
        "duration_us": round(duration, 3),
        "n_spans": len(spans),
        "n_events": len(trace_events),
        "root": path[0]["name"] if path else None,
        "critical_path": path,
        "critical_path_us": round(sum(s["self_us"] for s in path), 3),
        "stage_self_us": {k: round(v, 3)
                          for k, v in sorted(stages.items(),
                                             key=lambda kv: -kv[1])},
        "markers": [e.get("name") for e in trace_events
                    if e.get("ph") == "i"],
    }


def analyze(events: List[dict], top: int = 5,
            trace_id: Optional[str] = None) -> dict:
    """Full-export report: the top-k slowest traces (by covering root
    duration), or exactly one trace when `trace_id` is given (the
    exemplar-resolution path)."""
    traces = group_traces(events)
    if trace_id is not None:
        hits = {t: evs for t, evs in traces.items()
                if t == trace_id or t.startswith(trace_id)}
        reports = [analyze_trace(t, evs) for t, evs in hits.items()]
    else:
        reports = [analyze_trace(t, evs) for t, evs in traces.items()]
        reports.sort(key=lambda r: -r["duration_us"])
        reports = reports[:max(1, int(top))]
    return {
        "n_events": len(events),
        "n_traces": len(traces),
        "traces": reports,
    }


def format_report(report: dict, max_path: int = 24) -> str:
    """Human view: one block per trace — duration, stage breakdown, the
    critical path indented by tree depth order."""
    lines = [f"{report['n_traces']} trace(s) over {report['n_events']} "
             f"event(s); showing {len(report['traces'])}"]
    for tr in report["traces"]:
        lines.append("")
        lines.append(f"trace {tr['trace_id']} — "
                     f"{tr['duration_us'] / 1e3:.3f} ms, "
                     f"{tr['n_spans']} span(s), root {tr['root']}")
        if tr["markers"]:
            lines.append(f"  markers: {', '.join(tr['markers'])}")
        lines.append(f"  critical path "
                     f"({tr['critical_path_us'] / 1e3:.3f} ms):")
        for step in tr["critical_path"][:max_path]:
            lines.append(
                f"    {step['self_us'] / 1e3:9.3f} ms self "
                f"({step['dur_us'] / 1e3:9.3f} ms span)  {step['name']}")
        if len(tr["critical_path"]) > max_path:
            lines.append(f"    ... {len(tr['critical_path']) - max_path} "
                         "more")
        lines.append("  per-stage self time:")
        for name, us in tr["stage_self_us"].items():
            lines.append(f"    {us / 1e3:9.3f} ms  {name}")
    return "\n".join(lines)
