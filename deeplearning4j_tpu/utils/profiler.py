"""Profiling hooks: XLA trace capture + op-level summary.

Reference tier (SURVEY §5 tracing): listener-based throughput counters
only; deep profiling lived in external ND4J OpProfiler. TPU-native
answer: jax.profiler traces, captured either around a code block
(trace()) or per-N-iterations as a listener (ProfilerListener), plus a
parser that aggregates the captured xplane into per-op device time — the
exact workflow used to find this framework's BN backward regression
(f32 cotangent traffic), automated.
"""

from __future__ import annotations

import contextlib
import glob
import json
import logging
import os
import re
from collections import Counter
from typing import Dict, List, Optional, Tuple

import jax

from deeplearning4j_tpu.train.listeners import IterationListener

logger = logging.getLogger("deeplearning4j_tpu")


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax profiler trace around a block."""
    with jax.profiler.trace(log_dir):
        yield


def latest_xplane(log_dir: str) -> Optional[str]:
    hits = sorted(glob.glob(
        os.path.join(log_dir, "plugins/profile/*/*.xplane.pb")))
    return hits[-1] if hits else None


def op_summary(log_dir: str, top: int = 20,
               device_substr: str = "") -> List[Tuple[str, float]]:
    """Aggregate device-op wall time from the newest trace in log_dir.
    Returns [(op_name, seconds)] sorted desc. Needs the tensorflow xplane
    proto (present in this image); returns [] when unavailable."""
    path = latest_xplane(log_dir)
    if path is None:
        return []
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError:
        logger.warning("xplane proto unavailable; op_summary disabled")
        return []
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    stats: Counter = Counter()
    for plane in xs.planes:
        if not plane.name.startswith("/device:"):
            continue
        if device_substr and device_substr not in plane.name:
            continue
        meta = plane.event_metadata
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                stats[meta[ev.metadata_id].name] += ev.duration_ps / 1e12
    return stats.most_common(top)


def format_summary(rows: List[Tuple[str, float]]) -> str:
    lines = ["device op time (top):"]
    for name, sec in rows:
        lines.append(f"  {sec * 1e3:9.3f} ms  {name[:110]}")
    return "\n".join(lines)


# -- op-family aggregation (the PROFILE_*.md tables, mechanized) -------------

_FAMILY_STRIP = re.compile(r"(\.\d+)+$")


def op_family(name: str) -> str:
    """Collapse an XLA op instance name to its family: drop the HLO
    parameter list and trailing instance counters, so "fusion.123" /
    "%convert_reduce_fusion.5" both aggregate with their siblings — the
    exact grouping used by hand for the PROFILE_*.md tables."""
    base = name.split("(")[0].strip()
    base = base.lstrip("%")
    return _FAMILY_STRIP.sub("", base) or name


def family_summary(rows: List[Tuple[str, float]]) -> List[Tuple[str, float]]:
    """Aggregate [(op_name, seconds)] into [(family, seconds)] desc."""
    fam: Counter = Counter()
    for name, sec in rows:
        fam[op_family(name)] += sec
    return fam.most_common()


# XLA op-family name fragments -> cost-model primitive families, for the
# best-effort flops/bytes columns next to measured device time (fusions
# like convert_reduce_fusion have no single-primitive identity and stay
# unannotated — the full cost-model table rides in the `cost_model` block)
_FAMILY_TO_PRIMITIVE = (
    ("convolution", "conv_general_dilated"),
    ("dot", "dot_general"),
    ("gemm", "dot_general"),
    ("select-and-scatter", "select_and_scatter_add"),
    ("reduce-window", "reduce_window_sum"),
)


def roofline_columns(families_ms: dict, cost_model: Optional[dict]) -> dict:
    """Annotate measured XLA op families with the static cost model's
    flops/bytes where the family maps to ONE primitive (convolution ->
    conv_general_dilated, dot -> dot_general); fusions stay time-only.
    Gives PROFILE_*.md tables their roofline context columns."""
    if not cost_model:
        return {name: {"ms": ms} for name, ms in families_ms.items()}
    prim_fams = cost_model.get("families") or {}
    out = {}
    for name, ms in families_ms.items():
        row = {"ms": ms}
        low = name.lower()
        for frag, prim in _FAMILY_TO_PRIMITIVE:
            fc = prim_fams.get(prim)
            if frag in low and fc:
                row["flops"] = fc.get("flops")
                row["bytes"] = fc.get("bytes")
                row["cost_model_family"] = prim
                break
        out[name] = row
    return out


def write_profile_json(log_dir: str, path: str, top_ops: int = 40,
                       meta: Optional[dict] = None,
                       cost_model: Optional[dict] = None) -> dict:
    """Export the op-family aggregation of the newest trace in log_dir as
    a JSON artifact, so bench runs attach device-time breakdowns
    mechanically instead of by hand. Returns the payload (families empty
    when no xplane/proto is available — same degradation as op_summary).
    With `cost_model` (analysis/costmodel CostModel.to_dict()), the
    export carries per-family flops/bytes/roofline context next to the
    measured times instead of time alone."""
    rows = op_summary(log_dir, top=1_000_000)
    fams = family_summary(rows)
    families_ms = {name: round(sec * 1e3, 3) for name, sec in fams}
    payload = {
        "meta": meta or {},
        "log_dir": os.path.abspath(log_dir),
        "total_device_sec": round(sum(s for _, s in rows), 6),
        "families_ms": families_ms,
        "top_ops_ms": [
            {"op": name, "ms": round(sec * 1e3, 3)}
            for name, sec in rows[:top_ops]
        ],
    }
    if cost_model is not None:
        payload["cost_model"] = cost_model
        payload["families"] = roofline_columns(families_ms, cost_model)
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
    logger.info("profile JSON written to %s (%d families)", path, len(fams))
    return payload


class ProfilerListener(IterationListener):
    """Capture a trace for iterations [start, start+n_iterations) and log
    the op summary once finished (the listener-SPI packaging of the
    trace/parse workflow)."""

    def __init__(self, log_dir: str, start_iteration: int = 10,
                 n_iterations: int = 3, print_fn=None):
        self.log_dir = log_dir
        self.start = int(start_iteration)
        self.n = int(n_iterations)
        self.print_fn = print_fn or (lambda s: logger.info(s))
        self._active = False
        self._last_iteration = -1
        self.summary: List[Tuple[str, float]] = []

    def iteration_done(self, model, iteration, info):
        self._last_iteration = iteration
        if iteration == self.start and not self._active:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and iteration >= self.start + self.n:
            # force completion of the last step before closing the trace
            float(__import__("numpy").asarray(info["score"]()))
            self._finalize()

    def on_epoch_end(self, model, epoch):
        # training may end before the window closes — never leave the
        # process-global profiler running (a dangling trace blocks every
        # later start_trace and loses the xplane). A window that spans an
        # epoch boundary is finalized early, with a warning — place the
        # window inside one epoch for a full capture.
        if self._active:
            captured = self._last_iteration - self.start + 1
            if captured < self.n:
                logger.warning(
                    "profiler window truncated at epoch end (captured "
                    "%d of n_iterations=%d steps)", captured, self.n)
            if model._score is not None:  # complete the in-flight step
                float(__import__("numpy").asarray(model._score))
            self._finalize()

    def _finalize(self):
        jax.profiler.stop_trace()
        self._active = False
        self.summary = op_summary(self.log_dir)
        if self.summary:
            self.print_fn(format_summary(self.summary))
