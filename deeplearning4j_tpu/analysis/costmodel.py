"""Static device cost model over the train-step jaxpr — the static half
of the device performance/memory observability layer (utils/devprof.py
is the runtime half; each checks the other).

One `jax.make_jaxpr` of the FULL optimizer step (loss + backward +
updater — the same body every step jit uses, via `_make_step_body`) and
a walk over the program produces, per primitive family:

* **FLOPs** under HLO cost-analysis accounting: matmuls are 2·M·N·K,
  convolutions count only the *valid* (output, kernel-tap) pairs — SAME
  padding taps and dilation holes excluded, which is what makes
  backward-input convs (lhs_dilation = stride) come out right —
  elementwise ops are one FLOP per output element, reductions one per
  reduced element. `scan` bodies multiply by trip count (`flops`);
  a parallel accumulation counts loop bodies ONCE (`xla_flops_once`),
  matching XLA's own `Compiled.cost_analysis()` semantics so the two
  are directly comparable (the JX007 cross-check below).
* **bytes moved**: operand + result bytes per equation — the no-fusion
  upper bound on HBM traffic, the denominator of the roofline
  arithmetic-intensity classification.
* a **liveness-based activation peak**: one reverse pass computes each
  intermediate's last use; a forward pass then tracks the live-set byte
  watermark — the static analog of the `device_memory_bytes{kind=
  activations_est}` gauge utils/devprof.py publishes at runtime.

The model checks itself against XLA (`cross_check` → JX007 when the
divergence exceeds tolerance) and against the chip (`residency_findings`
→ JX008 when params + updater + data + activation peak exceed device
HBM). `utils/flops.py`'s hand-written per-layer estimator is demoted to
the fallback this model replaces (`flops.train_step_flops_for`).

Known accounting gaps, deliberate: `while` bodies count once (trip count
is not static); `cond` takes the most expensive branch; opaque custom
calls (pallas kernels) count zero — callers that need model FLOPs trace
with helpers disabled (flops.train_step_flops_for does), since model
FLOPs are implementation-independent.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax import core as jax_core

from deeplearning4j_tpu.analysis.findings import ERROR, Finding

# the MXU families — the "model FLOPs" numerator of the MFU accounting
# (elementwise/reduction work is bandwidth-, not FLOPs-bound on TPU, and
# excluding it keeps MFU comparable across frameworks)
MXU_FAMILIES = ("conv_general_dilated", "dot_general")

XLA_TOLERANCE = 0.10  # JX007 default: cost model vs cost_analysis()

_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "max", "min", "pow", "atan2", "rem",
    "neg", "abs", "sign", "exp", "exp2", "log", "log1p", "expm1", "tanh",
    "logistic", "sqrt", "rsqrt", "cbrt", "erf", "erfc", "erf_inv", "sin",
    "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "floor", "ceil",
    "round", "is_finite", "square", "integer_pow", "clamp", "select_n",
    "and", "or", "xor", "not", "eq", "ne", "lt", "le", "gt", "ge",
    "nextafter", "shift_left", "shift_right_logical",
    "shift_right_arithmetic",
})

# pure data movement: zero FLOPs, but bytes still count (that is the
# point — a transpose is free compute and real traffic)
_DATA_MOVEMENT = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "slice", "concatenate",
    "pad", "rev", "squeeze", "gather", "dynamic_slice",
    "dynamic_update_slice", "convert_element_type", "bitcast_convert_type",
    "iota", "copy", "device_put", "stop_gradient", "split",
})


def _size(v) -> int:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0
    n = 1
    for s in aval.shape:
        n *= int(s)
    return n


def _nbytes(v) -> int:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "dtype"):
        return 0
    return _size(v) * aval.dtype.itemsize


def _conv_valid_pairs(out_sz: int, k_sz: int, in_sz: int, stride: int,
                      pad_lo: int, w_dil: int, b_dil: int) -> int:
    """Valid (output position, kernel tap) pairs along ONE spatial dim:
    taps landing in padding or on base-dilation holes do no work, and
    HLO cost analysis does not count them. Separable across dims, so the
    multi-dim count is the product of the per-dim counts."""
    span = (in_sz - 1) * b_dil + 1
    n = 0
    for o in range(out_sz):
        base = o * stride - pad_lo
        for k in range(k_sz):
            pos = base + k * w_dil
            if 0 <= pos < span and pos % b_dil == 0:
                n += 1
    return n


def _conv_flops(eqn) -> float:
    dn = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    out = eqn.outvars[0].aval.shape
    batch_groups = eqn.params.get("batch_group_count", 1)
    strides = eqn.params["window_strides"]
    padding = eqn.params["padding"]
    ndims = len(strides)
    w_dil = eqn.params.get("rhs_dilation") or (1,) * ndims
    b_dil = eqn.params.get("lhs_dilation") or (1,) * ndims
    ls, rs, os_ = dn.lhs_spec, dn.rhs_spec, dn.out_spec
    batch = int(lhs[ls[0]])
    in_ch_per_group = int(rhs[rs[1]])
    out_ch = int(out[os_[1]])
    pairs = 1
    for i in range(ndims):
        pairs *= _conv_valid_pairs(
            int(out[os_[2 + i]]), int(rhs[rs[2 + i]]), int(lhs[ls[2 + i]]),
            strides[i], padding[i][0], w_dil[i], b_dil[i])
    return 2.0 * (batch // batch_groups) * out_ch * in_ch_per_group * pairs


def _same_pad_lo(in_sz: int, k_sz: int, stride: int) -> Tuple[int, int]:
    """(out_sz, pad_lo) of one spatial dim under XLA SAME padding:
    out = ceil(in/s), total pad = max((out-1)*s + k - in, 0), low half
    first (XLA puts the extra pad on the high side)."""
    out_sz = -(-in_sz // stride)
    pad_total = max((out_sz - 1) * stride + k_sz - in_sz, 0)
    return out_sz, pad_total // 2


def conv_instance_cost(*, kernel, stride, x_shape, n_out: int,
                       itemsize: int) -> dict:
    """FLOPs and minimal HBM bytes of ONE bias-free SAME NHWC conv
    instance, priced exactly like `_conv_flops` (HLO valid-pair
    accounting — taps landing in padding do no work). Bytes are the
    streaming floor: read x and w once, write y once; the fused stats
    epilogue adds nothing. This is the per-instance analogue of the
    per-family `CostModel.table()` rows, for kernel-routing decisions
    that must be made per shape rather than per program."""
    n, h, w, cin = (int(d) for d in x_shape)
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = int(stride[0]), int(stride[1])
    ho, ph = _same_pad_lo(h, kh, sh)
    wo, pw = _same_pad_lo(w, kw, sw)
    pairs = (_conv_valid_pairs(ho, kh, h, sh, ph, 1, 1)
             * _conv_valid_pairs(wo, kw, w, sw, pw, 1, 1))
    flops = 2.0 * n * n_out * cin * pairs
    bytes_ = itemsize * (n * h * w * cin + kh * kw * cin * n_out
                         + n * ho * wo * n_out)
    return {"flops": flops, "bytes": bytes_,
            "out_shape": (n, ho, wo, int(n_out))}


def bn_instance_cost(*, x_shape, itemsize: int, n_reads: int = 1,
                     n_writes: int = 1) -> dict:
    """FLOPs and bytes of one batch-norm pass over an NHWC activation:
    a handful of elementwise ops per element (priced at 4 FLOP/elem),
    `n_reads` full reads and `n_writes` full writes of the tensor.
    Per-channel vectors are noise and not counted."""
    numel = 1
    for d in x_shape:
        numel *= int(d)
    return {"flops": 4.0 * numel,
            "bytes": float(itemsize * numel * (n_reads + n_writes))}


def instance_roofline(flops: float, bytes_: float,
                      peak_flops: Optional[float] = None,
                      hbm_bandwidth: Optional[float] = None) -> dict:
    """Roofline verdict for a single op instance — the same ridge test
    `CostModel.table()` applies per family, exposed for per-shape kernel
    routing (`ops/pallas_conv_bn.conv_decision`). Off-TPU the v5e figures
    stand in: routing models the TPU the kernels target, not the host."""
    from deeplearning4j_tpu.utils import flops as _flops

    peak = peak_flops or _flops.peak_flops_per_chip()
    bw = hbm_bandwidth or _flops.hbm_bandwidth_per_chip()
    ridge = peak / bw
    intensity = flops / bytes_ if bytes_ else 0.0
    return {
        "flops": flops,
        "bytes": bytes_,
        "intensity": round(intensity, 3),
        "ridge_intensity": round(ridge, 3),
        "verdict": ("compute-bound" if intensity >= ridge
                    else "memory-bound"),
    }


def _eqn_flops(eqn) -> float:
    p = eqn.primitive.name
    if p == "dot_general":
        (contract_lhs, _), _ = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval.shape
        k = 1
        for d in contract_lhs:
            k *= int(lhs[d])
        return 2.0 * _size(eqn.outvars[0]) * k
    if p == "conv_general_dilated":
        return _conv_flops(eqn)
    if p in _ELEMENTWISE:
        return float(_size(eqn.outvars[0]))
    if p in _DATA_MOVEMENT:
        return 0.0
    if p.startswith("reduce_window"):
        return float(_size(eqn.invars[0]))
    if p.startswith("reduce_") or p in ("argmax", "argmin"):
        return float(max(
            sum(_size(v) for v in eqn.invars)
            - sum(_size(v) for v in eqn.outvars), 0))
    if p == "select_and_scatter_add":
        return float(_size(eqn.invars[0]) + _size(eqn.invars[1]))
    if p in ("cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp"):
        return float(_size(eqn.invars[0]))
    if p in ("scatter", "scatter_add", "scatter_mul", "scatter_min",
             "scatter_max"):
        return float(_size(eqn.invars[2]) if len(eqn.invars) > 2 else 0)
    if p == "sort":
        n = _size(eqn.invars[0])
        return float(n * max(1, int(np.log2(max(n, 2)))))
    return 0.0  # rng, custom calls, control flow shells


def _sub_jaxprs(eqn) -> List[jax_core.Jaxpr]:
    out = []
    for v in eqn.params.values():
        if isinstance(v, jax_core.ClosedJaxpr):
            out.append(v.jaxpr)
        elif isinstance(v, jax_core.Jaxpr):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            for item in v:
                if isinstance(item, jax_core.ClosedJaxpr):
                    out.append(item.jaxpr)
                elif isinstance(item, jax_core.Jaxpr):
                    out.append(item)
    return out


@dataclasses.dataclass
class FamilyCost:
    """Aggregate cost of one primitive family across the program."""

    flops: float = 0.0        # full execution (scan bodies × trip count)
    flops_once: float = 0.0   # loop bodies once (cost_analysis semantics)
    bytes: float = 0.0        # operand+result bytes, full execution
    count: int = 0            # equations (static, not per-iteration)

    def to_dict(self) -> dict:
        return {"flops": self.flops, "bytes": self.bytes,
                "count": self.count}


def _accumulate(jaxpr, families: Dict[str, FamilyCost],
                scale: float, scale_once: float):
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn)
        p = eqn.primitive.name
        if subs:
            if p == "cond":
                # most expensive branch only — both accumulations
                best, best_f = None, -1.0
                for sj in subs:
                    probe: Dict[str, FamilyCost] = {}
                    _accumulate(sj, probe, scale, scale_once)
                    f = sum(fc.flops for fc in probe.values())
                    if f > best_f:
                        best, best_f = probe, f
                for name, fc in (best or {}).items():
                    dst = families.setdefault(name, FamilyCost())
                    dst.flops += fc.flops
                    dst.flops_once += fc.flops_once
                    dst.bytes += fc.bytes
                    dst.count += fc.count
                continue
            mult = scale
            if p == "scan":
                mult = scale * int(eqn.params.get("length", 1))
            # while: trip count unknown — body counts once in BOTH views
            for sj in subs:
                _accumulate(sj, families, mult, scale_once)
            continue
        f = _eqn_flops(eqn)
        b = (sum(_nbytes(v) for v in eqn.invars)
             + sum(_nbytes(v) for v in eqn.outvars))
        fc = families.setdefault(p, FamilyCost())
        fc.flops += f * scale
        fc.flops_once += f * scale_once
        fc.bytes += b * scale
        fc.count += 1


def _activation_peak(jaxpr) -> Tuple[int, Optional[dict]]:
    """Liveness watermark over top-level intermediates: each outvar goes
    live when produced, dies after its last consumer (program outputs
    live to the end). Invars (params/updater/data) are resident, not
    activations — counted separately by the caller. Sub-jaxpr-calling
    equations are atomic: a scan's stacked residuals are its outvars, so
    the big backward-saved tensors ARE seen; per-iteration temps inside
    the body are not (an under- never an over-estimate)."""
    last_use: Dict[jax_core.Var, int] = {}
    n = len(jaxpr.eqns)
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, jax_core.Var):
                last_use[v] = i
    for v in jaxpr.outvars:
        if isinstance(v, jax_core.Var):
            last_use[v] = n
    produced = set()
    live_bytes = 0
    peak = 0
    largest: Optional[dict] = None
    dying: Dict[int, List[jax_core.Var]] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            if not isinstance(v, jax_core.Var) or v in produced:
                continue
            produced.add(v)
            nb = _nbytes(v)
            if nb:
                live_bytes += nb
                dying.setdefault(last_use.get(v, i), []).append(v)
                if largest is None or nb > largest["bytes"]:
                    aval = v.aval
                    largest = {"shape": tuple(int(s) for s in aval.shape),
                               "dtype": str(aval.dtype), "bytes": nb}
        peak = max(peak, live_bytes)
        for v in dying.pop(i, ()):
            live_bytes -= _nbytes(v)
    return peak, largest


@dataclasses.dataclass
class CostModel:
    """Per-family device cost of one traced program (usually one
    optimizer step), plus the static memory picture."""

    what: str
    families: Dict[str, FamilyCost]
    activation_peak_bytes: int
    largest_activation: Optional[dict]
    param_bytes: int = 0
    updater_bytes: int = 0
    data_bytes: int = 0
    const_bytes: int = 0
    # bytes (within param_bytes/updater_bytes) belonging to layers
    # declared `host_resident=True` (host-sharded embedding tables pulled
    # row-wise through the paramserver) — they never occupy device HBM,
    # so resident_bytes exempts them
    host_resident_param_bytes: int = 0
    host_resident_updater_bytes: int = 0
    # the traced on-device step also holds the table AND its cotangent
    # (the dense scatter-add gradient) live in the activation peak; the
    # pipeline keeps both host-side (rows pulled, row deltas pushed), so
    # that table-shaped share of the peak is exempt too (clamped to the
    # measured peak — an estimator, never negative)
    host_resident_activation_bytes: int = 0
    batch: Optional[int] = None
    # data-axis shard count of the net this step was traced from (1 for
    # single-device nets): the traced program is the GLOBAL step, so
    # every per-chip view divides batch-sharded quantities by this
    data_axis_shards: int = 1
    # the priced gradient-collective schedule (parallel/sharded
    # CollectivePlan.describe via the net's MeshPlan): wire bytes per
    # step at the configured grad dtype, bucket sizes, and the ring
    # all-reduce time estimate. None for single-device nets. Priced
    # SEPARATELY from the FLOP families — attaching it must never move
    # model_flops (JX007 guards that)
    collective: Optional[dict] = None

    @property
    def flops_total(self) -> float:
        return sum(fc.flops for fc in self.families.values())

    @property
    def xla_comparable_flops(self) -> float:
        """FLOPs with loop bodies counted ONCE — the number comparable
        to `Compiled.cost_analysis()['flops']` (XLA does not multiply a
        While body by its trip count)."""
        return sum(fc.flops_once for fc in self.families.values())

    @property
    def bytes_total(self) -> float:
        return sum(fc.bytes for fc in self.families.values())

    @property
    def model_flops(self) -> float:
        """MXU-family FLOPs only — the MFU numerator (GLOBAL: the whole
        traced step across all data shards)."""
        return sum(fc.flops for name, fc in self.families.items()
                   if name in MXU_FAMILIES)

    @property
    def model_flops_per_chip(self) -> float:
        """model_flops divided by the data-axis size — the per-chip MFU
        numerator. Using the global figure against one chip's peak would
        over-report multi-chip MFU data_axis_shards×."""
        return self.model_flops / max(1, self.data_axis_shards)

    @property
    def resident_bytes(self) -> int:
        """Static peak-memory estimate PER CHIP: everything that must be
        in one device's HBM at once during the step — params/updater/
        consts replicated (full size per chip), data and activations
        batch-sharded (divided by the data-axis size). Params held twice
        when not donated is deliberately NOT modeled — JX006 audits
        donation separately. Host-resident tables (sparse embedding
        weights served row-wise by the paramserver) are subtracted —
        they live in host RAM, not HBM."""
        n = max(1, self.data_axis_shards)
        device_param = self.param_bytes - self.host_resident_param_bytes
        device_upd = self.updater_bytes - self.host_resident_updater_bytes
        device_act = max(
            0, self.activation_peak_bytes - self.host_resident_activation_bytes)
        return (device_param + device_upd + self.const_bytes
                + (self.data_bytes + device_act) // n)

    def roofline(self, peak_flops: Optional[float] = None,
                 hbm_bandwidth: Optional[float] = None) -> dict:
        """Program-level roofline verdict: the step-time lower bound is
        max(compute, traffic) at the given peak; the MFU ceiling is what
        model FLOPs could at best achieve against that bound. Per-chip:
        a sharded step's work divides across the data axis before
        meeting one chip's peak."""
        from deeplearning4j_tpu.utils import flops as _flops

        peak = peak_flops or _flops.peak_flops_per_chip()
        bw = hbm_bandwidth or _flops.hbm_bandwidth_per_chip()
        n = max(1, self.data_axis_shards)
        t_compute = self.flops_total / n / peak
        t_memory = self.bytes_total / n / bw
        bound = max(t_compute, t_memory, 1e-30)
        out = {
            "peak_flops": peak,
            "hbm_bandwidth": bw,
            "ridge_intensity": peak / bw,
            "compute_seconds": t_compute,
            "memory_seconds": t_memory,
            "bound": "compute" if t_compute >= t_memory else "memory",
            "step_time_lower_bound_seconds": bound,
            "mfu_ceiling": self.model_flops_per_chip / (peak * bound),
        }
        if self.collective is not None:
            # the gradient all-reduce rides along unpriced in the bound:
            # the bucketed schedule exists to OVERLAP it with compute, so
            # the honest statement is "hidden if collective <= bound" —
            # reported, never silently added to the lower bound
            t_coll = self.collective.get("ring_estimate_seconds")
            out["collective_seconds"] = t_coll
            if t_coll is not None:
                out["collective_hidden_by_compute"] = bool(t_coll <= bound)
        return out

    def table(self, peak_flops: Optional[float] = None,
              hbm_bandwidth: Optional[float] = None) -> List[dict]:
        """Per-family rows, FLOPs-descending, each classified compute-
        vs memory-bound against the roofline ridge intensity."""
        from deeplearning4j_tpu.utils import flops as _flops

        peak = peak_flops or _flops.peak_flops_per_chip()
        bw = hbm_bandwidth or _flops.hbm_bandwidth_per_chip()
        ridge = peak / bw
        rows = []
        for name, fc in sorted(self.families.items(),
                               key=lambda kv: -kv[1].flops):
            intensity = fc.flops / fc.bytes if fc.bytes else 0.0
            rows.append({
                "family": name,
                "count": fc.count,
                "flops": fc.flops,
                "bytes": fc.bytes,
                "intensity": round(intensity, 3),
                "verdict": ("compute-bound" if intensity >= ridge
                            else "memory-bound"),
                "mxu": name in MXU_FAMILIES,
            })
        return rows

    def to_dict(self) -> dict:
        return {
            "what": self.what,
            "batch": self.batch,
            "flops_total": self.flops_total,
            "xla_comparable_flops": self.xla_comparable_flops,
            "bytes_total": self.bytes_total,
            "model_flops": self.model_flops,
            "activation_peak_bytes": self.activation_peak_bytes,
            "largest_activation": self.largest_activation,
            "param_bytes": self.param_bytes,
            "updater_bytes": self.updater_bytes,
            "host_resident_param_bytes": self.host_resident_param_bytes,
            "host_resident_updater_bytes": self.host_resident_updater_bytes,
            "host_resident_activation_bytes":
                self.host_resident_activation_bytes,
            "data_bytes": self.data_bytes,
            "const_bytes": self.const_bytes,
            "data_axis_shards": self.data_axis_shards,
            "model_flops_per_chip": self.model_flops_per_chip,
            "resident_bytes": self.resident_bytes,
            "collective": self.collective,
            "families": {k: v.to_dict() for k, v in self.families.items()},
        }


def cost_closed_jaxpr(closed: jax_core.ClosedJaxpr,
                      what: str = "program") -> CostModel:
    families: Dict[str, FamilyCost] = {}
    _accumulate(closed.jaxpr, families, 1.0, 1.0)
    peak, largest = _activation_peak(closed.jaxpr)
    const_bytes = sum(int(getattr(c, "nbytes", 0) or 0)
                      for c in closed.consts)
    return CostModel(what=what, families=families,
                     activation_peak_bytes=peak, largest_activation=largest,
                     const_bytes=const_bytes)


def cost_fn(fn: Callable, *args, what: str = "fn") -> CostModel:
    """Cost-model any jittable callable on abstract or concrete args."""
    return cost_closed_jaxpr(jax.make_jaxpr(fn)(*args), what=what)


# -- the train step of a network ---------------------------------------------


def _tree_bytes(tree) -> int:
    return sum(int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
               for leaf in jax.tree_util.tree_leaves(tree)
               if hasattr(leaf, "shape"))


def train_step_args(net, *, batch_size: int = 8, timesteps: int = 16):
    """(step_fn, args) of the FULL optimizer step — the same body every
    step jit compiles (`_make_step_body`: loss, backward, gradient
    normalization, updater, param update) on an abstract batch shaped
    from the conf's InputTypes via shapeflow. Shared by the cost model
    and the XLA cross-check so both sides measure the same program.
    Raises ValueError when the conf has no InputType to shape a batch."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.analysis import shapeflow
    from deeplearning4j_tpu.analysis.jaxpr_audit import (
        _features_sds,
        _labels_sds,
    )
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration

    net._require_init()
    conf = net.conf
    rng = jax.random.PRNGKey(0)

    if isinstance(conf, MultiLayerConfiguration):
        x = _features_sds(conf.input_type, batch_size, timesteps)
        out_types = shapeflow.propagate_types(conf)
        y = _labels_sds(out_types[-1] if out_types else None,
                        batch_size, timesteps)
        if x is None or y is None:
            raise ValueError(
                "no InputType on the configuration — cannot shape an "
                "abstract batch for the cost model")
        body = net._make_step_body(net._std_loss_builder())

        def step(params, states, upd_state, x, y, lr, t, rng):
            return body(params, states, upd_state, (x, y, None, None),
                        lr, t, rng)

        args = (net.params_list, net.state_list, net.upd_state, x, y,
                jnp.float32(0.1), jnp.float32(1.0), rng)
    else:
        if conf.input_types is None:
            raise ValueError(
                "no InputTypes on the configuration — cannot shape an "
                "abstract batch for the cost model")
        xs = tuple(_features_sds(t, batch_size, timesteps)
                   for t in conf.input_types)
        types = shapeflow.propagate_types(conf)
        ys = tuple(_labels_sds(types.get(name), batch_size, timesteps)
                   for name in conf.outputs)
        if any(v is None for v in xs) or any(v is None for v in ys):
            raise ValueError(
                "could not shape abstract features/labels from the "
                "graph's InputTypes")
        body = net._make_step_body()

        def step(params, states, upd_state, xs, ys, lr, t, rng):
            return body(params, states, upd_state, (xs, ys, None, None),
                        lr, t, rng)

        args = (net.params_list, net.state_list, net.upd_state, xs, ys,
                jnp.float32(0.1), jnp.float32(1.0), rng)
    return step, args


def _host_resident_bytes(net) -> Tuple[int, int]:
    """(param, updater) bytes of layers tagged `host_resident=True` —
    host-sharded embedding tables served by the paramserver. Walks
    `_ordered_layer_confs()` (aligned with params_list / upd_state on
    both MLN and graph); a net without that surface is simply all
    device-resident."""
    try:
        confs = net._ordered_layer_confs()
        params = net.params_list
        upd = getattr(net, "upd_state", None) or [None] * len(params)
    except Exception:
        return 0, 0
    hp = hu = 0
    for i, conf in enumerate(confs):
        if not getattr(conf, "host_resident", False):
            continue
        if i < len(params):
            hp += _tree_bytes(params[i])
        if i < len(upd):
            hu += _tree_bytes(upd[i])
    return hp, hu


def _model_of_step(net, step, args, batch_size: int) -> CostModel:
    """Trace + static memory bookkeeping shared by train_step_cost and
    check_network (args[3:5] are the feature/label structs (MLN) or
    tuples (graph))."""
    cm = cost_fn(step, *args, what=f"{type(net).__name__}:train_step")
    cm.batch = int(batch_size)
    cm.param_bytes = _tree_bytes(net.params_list)
    cm.updater_bytes = _tree_bytes(net.upd_state)
    cm.data_bytes = _tree_bytes((args[3], args[4]))
    hp, hu = _host_resident_bytes(net)
    cm.host_resident_param_bytes = hp
    cm.host_resident_updater_bytes = hu
    # table + its cotangent ride the activation peak in the traced
    # device program; host-side they are paramserver traffic, not HBM
    cm.host_resident_activation_bytes = min(
        int(cm.activation_peak_bytes), 2 * hp)
    plan = getattr(net, "_mesh_plan", None)
    if plan is not None:
        cm.data_axis_shards = max(1, int(plan.n_data_shards))
        try:
            cm.collective = plan.collective_describe(net)
        except Exception:
            cm.collective = None  # pricing must never sink the model
    return cm


def train_step_cost(net, *, batch_size: int = 8,
                    timesteps: int = 16) -> CostModel:
    """Cost-model `net`'s full optimizer step at the given batch shape.
    One abstract trace — no compile, no device step, no mutation."""
    step, args = train_step_args(net, batch_size=batch_size,
                                 timesteps=timesteps)
    return _model_of_step(net, step, args, batch_size)


# -- cross-checks -------------------------------------------------------------


def xla_cost_analysis(fn: Callable, *args) -> Optional[dict]:
    """XLA's own post-optimization accounting of the same program:
    `{'flops': ..., 'bytes_accessed': ...}`, or None when the backend
    does not expose cost analysis (never raises — skip, don't fail)."""
    try:
        compiled = jax.jit(fn).lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not isinstance(ca, dict) or "flops" not in ca:
            return None
        flops = float(ca["flops"])
        if flops <= 0:
            # some backends report -1/0 when the figure is unavailable;
            # a non-positive denominator would make the JX007 check
            # vacuously green (or divide by zero) — treat as absent
            return None
        return {"flops": flops,
                "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
    except Exception:
        return None


def cross_check(cm: CostModel, xla_stats: Optional[dict],
                tolerance: float = XLA_TOLERANCE) -> List[Finding]:
    """JX007: the static model's loop-bodies-once FLOP total must agree
    with XLA's cost_analysis within `tolerance` — the self-check that
    keeps every MFU/roofline number built on this model falsifiable.
    No XLA stats available -> no finding (the check is skip-, not
    fail-silent: callers report `xla: unavailable`)."""
    if not xla_stats or not xla_stats.get("flops"):
        return []
    ours = cm.xla_comparable_flops
    theirs = xla_stats["flops"]
    rel = abs(ours - theirs) / theirs
    if rel <= tolerance:
        return []
    return [Finding(
        "JX007", ERROR, f"costmodel:{cm.what}",
        f"cost model diverges from XLA cost_analysis by {rel:.1%} "
        f"(model {ours:.4g} vs XLA {theirs:.4g} flops, tolerance "
        f"{tolerance:.0%}) — MFU/roofline numbers built on this model "
        "are not trustworthy for this program",
        "a primitive family is mis-accounted: compare per-family totals "
        "(`cli perf --json`) against the program and fix the rule",
        name=f"JX007:costmodel:{cm.what}")]


def residency_findings(cm: CostModel,
                       hbm_bytes: Optional[float] = None) -> List[Finding]:
    """JX008: static residency (params + updater + data + consts +
    activation liveness peak) exceeding device HBM — the step will
    RESOURCE_EXHAUSTED before it ever runs. Skipped when the chip's HBM
    size is unknown (CPU backends)."""
    if hbm_bytes is None:
        from deeplearning4j_tpu.utils import flops as _flops

        hbm_bytes = _flops.peak_hbm_bytes_per_chip()
    if not hbm_bytes:
        return []
    resident = cm.resident_bytes
    if resident <= hbm_bytes:
        return []
    exempt = cm.host_resident_param_bytes + cm.host_resident_updater_bytes
    exempt_note = (f"; {exempt / 2**30:.2f} GiB of host-resident tables "
                   "already exempted" if exempt else "")
    return [Finding(
        "JX008", ERROR, f"costmodel:{cm.what}",
        f"static peak memory estimate {resident / 2**30:.2f} GiB exceeds "
        f"device HBM {hbm_bytes / 2**30:.2f} GiB (activations "
        f"{cm.activation_peak_bytes / 2**30:.2f} GiB, params "
        f"{cm.param_bytes / 2**30:.2f} GiB, updater "
        f"{cm.updater_bytes / 2**30:.2f} GiB{exempt_note}) — the step "
        "will OOM before it runs",
        "shrink the batch, enable rematerialization, shard the model "
        "(parallel/ tensor/pipeline parallelism), or mark embedding "
        "tables host_resident and serve them via the paramserver",
        name=f"JX008:costmodel:{cm.what}")]


def check_network(net, *, batch_size: int = 8, timesteps: int = 16,
                  tolerance: float = XLA_TOLERANCE,
                  compile_xla: bool = False,
                  hbm_bytes: Optional[float] = None
                  ) -> Tuple[CostModel, Optional[dict], List[Finding]]:
    """The full static check: cost-model the train step, optionally
    compile it for the XLA cross-check (JX007 — expensive: a real
    compile), and check static residency against HBM (JX008). Returns
    (model, xla stats or None, findings)."""
    step, args = train_step_args(net, batch_size=batch_size,
                                 timesteps=timesteps)
    cm = _model_of_step(net, step, args, batch_size)
    xla_stats = xla_cost_analysis(step, *args) if compile_xla else None
    findings = cross_check(cm, xla_stats, tolerance=tolerance)
    findings += residency_findings(cm, hbm_bytes=hbm_bytes)
    return cm, xla_stats, findings
