"""Overlapped sparse-embedding pipeline (parallel/sparse.py): book
conservation, byte-identical prefetch-on/off trajectories, deadlines
honored through the cache, thread hygiene, analyzer host-residency
exemptions (JX005/JX008), and per-tenant pull spend."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.recsys import zipf_ids
from deeplearning4j_tpu.parallel.paramserver import (
    EmbeddingParameterServer,
    EmbeddingPSClient,
)
from deeplearning4j_tpu.parallel.sparse import (
    SPARSE_THREAD_PREFIX,
    SparseEmbeddingPipeline,
)
from deeplearning4j_tpu.utils import faultpoints as fp


def _sparse_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith(SPARSE_THREAD_PREFIX)]


def _start_servers(init, n):
    servers = [EmbeddingParameterServer({"emb": init.copy()})
               for _ in range(n)]
    urls = [f"http://127.0.0.1:{s.start()}" for s in servers]
    return servers, urls


def _run_arm(init, batches, *, prefetch, cache_rows, lr=0.1):
    """One training arm over fresh 2-endpoint servers; returns the final
    table (pulled after a full flush) and the pipeline's stats dict."""
    servers, urls = _start_servers(init, 2)
    try:
        client = EmbeddingPSClient(urls)
        try:
            with SparseEmbeddingPipeline(client, "emb",
                                         cache_rows=cache_rows,
                                         prefetch=prefetch) as pipe:
                for k, ids in enumerate(batches):
                    rows = pipe.lookup(ids)
                    if k + 1 < len(batches):
                        pipe.prefetch(batches[k + 1])
                    pipe.push(ids, (-lr * rows).astype(np.float32))
                stats = pipe.stats()
            assert client.flush(timeout=30.0) is True
            final = client.pull("emb", np.arange(init.shape[0]))
        finally:
            client.close()
    finally:
        for s in servers:
            s.stop()
    return final, stats


def test_books_conserve_and_duplicates_coalesce():
    """pull_rows == cache_hit + cache_miss exactly, and duplicate ids in
    a batch are coalesced (counted, pulled once)."""
    rng = np.random.default_rng(0)
    init = rng.standard_normal((64, 8)).astype(np.float32)
    # heavy duplication: 48 ids over a 16-id range
    batches = [rng.integers(0, 16, size=48) for _ in range(5)]
    final, stats = _run_arm(init, batches, prefetch=True, cache_rows=32)
    assert stats["pull_rows"] == stats["cache_hit"] + stats["cache_miss"], \
        stats
    assert stats["coalesced"] > 0, stats
    assert stats["cache_hit"] > 0, stats  # repeated ids hit the hot cache
    assert final.shape == init.shape


def test_lookup_returns_rows_in_order_with_duplicates():
    rng = np.random.default_rng(1)
    init = rng.standard_normal((32, 4)).astype(np.float32)
    servers, urls = _start_servers(init, 2)
    try:
        client = EmbeddingPSClient(urls)
        try:
            with SparseEmbeddingPipeline(client, "emb",
                                         cache_rows=8) as pipe:
                ids = np.array([5, 0, 5, 31, 0])
                got = pipe.lookup(ids)
                np.testing.assert_allclose(got, init[ids], rtol=1e-6)
                # second lookup of the same ids is all cache hits
                got2 = pipe.lookup(ids)
                np.testing.assert_allclose(got2, init[ids], rtol=1e-6)
                s = pipe.stats()
                assert s["cache_hit"] == 3 and s["cache_miss"] == 3, s
        finally:
            client.close()
    finally:
        for s in servers:
            s.stop()


def test_prefetch_on_off_trajectories_byte_identical():
    """The acceptance bar: cache + prefetch + write-through must be
    TRANSPARENT — same batches, same updates, byte-identical final
    table with the pipeline on vs the synchronous no-cache arm."""
    rng = np.random.default_rng(2)
    init = (rng.standard_normal((48, 6)) * 0.5).astype(np.float32)
    batches = [zipf_ids(24, 48, alpha=1.3, seed=100 + k)
               for k in range(8)]
    on, s_on = _run_arm(init, batches, prefetch=True, cache_rows=12)
    off, s_off = _run_arm(init, batches, prefetch=False, cache_rows=0)
    assert on.tobytes() == off.tobytes(), \
        (np.abs(on - off).max(), s_on, s_off)
    assert s_on["pull_rows"] == s_on["cache_hit"] + s_on["cache_miss"]
    assert s_off["cache_hit"] == 0  # the alternate arm really is cold


def test_deadline_honored_through_cache_and_under_outage():
    """A wedged endpoint must not stall lookup() past deadline_ms even
    when the rows were prefetched; fully-cached lookups still serve
    (no RPC on the hot path) while the endpoint hangs."""
    rng = np.random.default_rng(3)
    init = rng.standard_normal((32, 4)).astype(np.float32)
    servers, urls = _start_servers(init, 1)
    try:
        client = EmbeddingPSClient(urls)
        try:
            with SparseEmbeddingPipeline(client, "emb",
                                         cache_rows=32) as pipe:
                warm = np.arange(8)
                pipe.lookup(warm)  # fill the cache before the outage
                plan = fp.FaultPlan(seed=0)
                plan.add("paramserver_rpc", "hang", p=1.0,
                         hang_seconds=3.0)
                cold = np.arange(16, 24)
                with fp.active(plan):
                    # cached rows: zero RPCs, deadline trivially met
                    got = pipe.lookup(warm, deadline_ms=500)
                    np.testing.assert_allclose(got, init[warm], rtol=1e-6)
                    # cold rows ride a prefetch that is now wedged
                    pipe.prefetch(cold)
                    start = time.monotonic()
                    with pytest.raises(TimeoutError):
                        pipe.lookup(cold, deadline_ms=300)
                    wall = time.monotonic() - start
                    assert wall < 2.0, f"deadline overshot: {wall:.1f}s"
                # endpoint recovered: the same rows resolve inline
                got = pipe.lookup(cold)
                np.testing.assert_allclose(got, init[cold], rtol=1e-6)
        finally:
            client.close()
    finally:
        for s in servers:
            s.stop()


def test_push_write_through_keeps_cache_coherent():
    """A push to a cached row updates the cached copy in place — the
    next lookup returns the post-update value from cache, and after a
    flush the server agrees."""
    init = np.zeros((16, 4), np.float32)
    servers, urls = _start_servers(init, 2)
    try:
        client = EmbeddingPSClient(urls)
        try:
            with SparseEmbeddingPipeline(client, "emb",
                                         cache_rows=16) as pipe:
                ids = np.array([2, 3])
                pipe.lookup(ids)
                pipe.push(ids, np.ones((2, 4), np.float32))
                got = pipe.lookup(ids)  # served write-through, no flush
                np.testing.assert_allclose(got, np.ones((2, 4)), rtol=1e-6)
            assert client.flush(timeout=30.0) is True
            final = client.pull("emb", ids)
            np.testing.assert_allclose(final, np.ones((2, 4)), rtol=1e-6)
        finally:
            client.close()
    finally:
        for s in servers:
            s.stop()


def test_close_leaves_no_sparse_threads():
    init = np.zeros((8, 2), np.float32)
    servers, urls = _start_servers(init, 1)
    try:
        client = EmbeddingPSClient(urls)
        try:
            pipe = SparseEmbeddingPipeline(client, "emb", cache_rows=4)
            pipe.lookup(np.array([0, 1]))
            pipe.prefetch(np.array([2, 3]))
            assert _sparse_threads()  # the prefetch worker is live
            pipe.close()
            pipe.close()  # idempotent
            assert not _sparse_threads(), _sparse_threads()
            with pytest.raises(RuntimeError):
                pipe.lookup(np.array([0]))
            with pytest.raises(RuntimeError):
                pipe.prefetch(np.array([0]))
        finally:
            client.close()
    finally:
        for s in servers:
            s.stop()


def test_jx008_host_resident_table_exempt_device_side_fails():
    """The regression the analyzers satellite demands: a multi-x-HBM
    embedding table marked host_resident passes residency (JX008), the
    SAME table device-side still fails."""
    from deeplearning4j_tpu.analysis import costmodel as cmod
    from deeplearning4j_tpu.models.recsys import recsys_network

    hbm = 16 * 2 ** 20  # 16 MiB "chip"; the table below is 25.6 MB
    vocab, dim = 100_000, 64

    host = recsys_network(vocab=vocab, dim=dim, hidden=16,
                          host_resident=True)
    cm_host = cmod.train_step_cost(host, batch_size=8)
    assert cm_host.host_resident_param_bytes >= vocab * dim * 4
    assert cmod.residency_findings(cm_host, hbm_bytes=hbm) == []

    dev = recsys_network(vocab=vocab, dim=dim, hidden=16,
                         host_resident=False)
    cm_dev = cmod.train_step_cost(dev, batch_size=8)
    assert cm_dev.host_resident_param_bytes == 0
    found = cmod.residency_findings(cm_dev, hbm_bytes=hbm)
    assert [f.code for f in found] == ["JX008"], found


def test_jx005_quiet_on_host_resident_table():
    """The host-resident table's rows enter the jitted step as data, not
    as a traced parameter — the dead-arg audit (JX005) must not flag the
    table (or anything else in the recsys tower)."""
    from deeplearning4j_tpu.analysis.jaxpr_audit import audit_network
    from deeplearning4j_tpu.models.recsys import recsys_network

    net = recsys_network(vocab=4096, dim=16, hidden=16,
                         host_resident=True)
    findings = audit_network(net, batch_size=4)
    assert not [f for f in findings if f.code == "JX005"], findings


def test_pull_spend_books_to_tenant_under_paramserver_tier():
    from deeplearning4j_tpu.utils import resourcemeter
    from deeplearning4j_tpu.utils.metrics import get_registry

    tenant = "sparse-spend-test"

    def tier_spend():
        spend = resourcemeter.spend_table(get_registry().scalar_values())
        return (spend.get(tenant, {}).get("device_seconds", {})
                .get(resourcemeter.TIER_PARAMSERVER, 0.0))

    resourcemeter.enable()
    try:
        before = tier_spend()
        init = np.zeros((32, 4), np.float32)
        servers, urls = _start_servers(init, 2)
        try:
            client = EmbeddingPSClient(urls, tenant=tenant)
            try:
                with SparseEmbeddingPipeline(client, "emb", cache_rows=8,
                                             tenant=tenant) as pipe:
                    pipe.lookup(np.arange(16))
            finally:
                client.close()
        finally:
            for s in servers:
                s.stop()
        after = tier_spend()
        assert after > before, (before, after)
        verdict = resourcemeter.conservation(get_registry().scalar_values())
        assert verdict["ok"], verdict
    finally:
        resourcemeter.disable()
