"""Updaters (optimizers) with the reference's semantics.

Reference: nd4j GradientUpdater implementations driven through
nn/updater/BaseMultiLayerUpdater.update (gradient normalization in preApply
:284-325, then per-UpdaterBlock fused state update, UpdaterBlock.java:101)
and the Updater enum (nn/conf/Updater.java).

TPU-first shape: an updater is a pair of pure functions

    init(params)                          -> state pytree
    apply(grads, state, lr, t)            -> (updates, new_state)

applied leaf-wise over the whole parameter pytree inside the jitted train
step. XLA fuses every leaf's update math into the step program — the same
effect as the reference's "one fused view update per UpdaterBlock"
(UpdaterBlock.java:24-101), achieved by the compiler instead of manual flat
views. `updates` are deltas to ADD to params (minimize: updates = -lr*...).

Learning-rate schedules (reference: LearningRatePolicy + per-iteration maps)
are computed host-side per step and passed in as the scalar `lr`, so no
recompilation per iteration.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class UpdaterDef:
    """A concrete updater: init + leafwise apply."""

    name: str
    init: Callable[[Any], Any]  # leaf -> state dict for that leaf
    apply: Callable[..., Any]  # (g, state, lr, t, hp) -> (update, new_state)
    hyper: Dict[str, float]

    def init_tree(self, params):
        return jax.tree_util.tree_map(self.init, params)

    def apply_tree(self, grads, state, lr_tree, t):
        """lr_tree: per-leaf learning rate (scalar or tree matching params)."""
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_s = treedef.flatten_up_to(state)
        if isinstance(lr_tree, (float, int)) or (
            hasattr(lr_tree, "ndim") and lr_tree.ndim == 0
        ):
            flat_lr = [lr_tree] * len(flat_g)
        else:
            flat_lr = treedef.flatten_up_to(lr_tree)
        out_u, out_s = [], []
        for g, s, lr in zip(flat_g, flat_s, flat_lr):
            u, ns = self.apply(g, s, lr, t, self.hyper)
            out_u.append(u)
            out_s.append(ns)
        return (
            jax.tree_util.tree_unflatten(treedef, out_u),
            jax.tree_util.tree_unflatten(treedef, out_s),
        )


# -- implementations ---------------------------------------------------------

def _sgd(hyper):
    def init(p):
        return ()

    def apply(g, s, lr, t, hp):
        return -lr * g, s

    return UpdaterDef("sgd", init, apply, hyper)


def _nesterovs(hyper):
    """Nesterov momentum, reference formulation (nd4j Nesterovs.java):
    vNew = mu*v - lr*g;  update = -mu*v + (1+mu)*vNew."""

    def init(p):
        return {"v": jnp.zeros_like(p)}

    def apply(g, s, lr, t, hp):
        mu = hp["momentum"]
        v = s["v"]
        v_new = mu * v - lr * g
        return -mu * v + (1.0 + mu) * v_new, {"v": v_new}

    return UpdaterDef("nesterovs", init, apply, hyper)


def _adam(hyper):
    def init(p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}

    def apply(g, s, lr, t, hp):
        b1, b2, eps = hp["beta1"], hp["beta2"], hp["epsilon"]
        m = b1 * s["m"] + (1 - b1) * g
        v = b2 * s["v"] + (1 - b2) * (g * g)
        # bias correction with t counted from 1
        tt = t + 1.0
        mhat = m / (1 - b1**tt)
        vhat = v / (1 - b2**tt)
        return -lr * mhat / (jnp.sqrt(vhat) + eps), {"m": m, "v": v}

    return UpdaterDef("adam", init, apply, hyper)


def _adamax(hyper):
    def init(p):
        return {"m": jnp.zeros_like(p), "u": jnp.zeros_like(p)}

    def apply(g, s, lr, t, hp):
        b1, b2, eps = hp["beta1"], hp["beta2"], hp["epsilon"]
        m = b1 * s["m"] + (1 - b1) * g
        u = jnp.maximum(b2 * s["u"], jnp.abs(g))
        tt = t + 1.0
        return -lr * m / ((1 - b1**tt) * (u + eps)), {"m": m, "u": u}

    return UpdaterDef("adamax", init, apply, hyper)


def _adadelta(hyper):
    """Reference AdaDelta (nd4j AdaDelta.java): no learning rate; uses rho
    and epsilon. The passed lr is ignored, matching the reference."""

    def init(p):
        return {"msg": jnp.zeros_like(p), "msdx": jnp.zeros_like(p)}

    def apply(g, s, lr, t, hp):
        rho, eps = hp["rho"], hp["epsilon"]
        msg = rho * s["msg"] + (1 - rho) * g * g
        dx = -g * jnp.sqrt(s["msdx"] + eps) / jnp.sqrt(msg + eps)
        msdx = rho * s["msdx"] + (1 - rho) * dx * dx
        return dx, {"msg": msg, "msdx": msdx}

    return UpdaterDef("adadelta", init, apply, hyper)


def _adagrad(hyper):
    def init(p):
        return {"h": jnp.zeros_like(p)}

    def apply(g, s, lr, t, hp):
        eps = hp["epsilon"]
        h = s["h"] + g * g
        return -lr * g / (jnp.sqrt(h) + eps), {"h": h}

    return UpdaterDef("adagrad", init, apply, hyper)


def _rmsprop(hyper):
    def init(p):
        return {"r": jnp.zeros_like(p)}

    def apply(g, s, lr, t, hp):
        decay, eps = hp["rms_decay"], hp["epsilon"]
        r = decay * s["r"] + (1 - decay) * g * g
        return -lr * g / (jnp.sqrt(r) + eps), {"r": r}

    return UpdaterDef("rmsprop", init, apply, hyper)


def _none(hyper):
    def init(p):
        return ()

    def apply(g, s, lr, t, hp):
        return jnp.zeros_like(g), s

    return UpdaterDef("none", init, apply, hyper)


def make_updater(
    name: str,
    learning_rate: float = 0.1,
    momentum: float = 0.9,
    rho: float = 0.95,
    rms_decay: float = 0.95,
    beta1: float = 0.9,
    beta2: float = 0.999,
    epsilon: float = 1e-8,
) -> UpdaterDef:
    hyper = dict(momentum=momentum, rho=rho, rms_decay=rms_decay,
                 beta1=beta1, beta2=beta2, epsilon=epsilon,
                 learning_rate=learning_rate)
    n = name.lower()
    factory = {
        "sgd": _sgd,
        "nesterovs": _nesterovs,
        "adam": _adam,
        "adamax": _adamax,
        "adadelta": _adadelta,
        "adagrad": _adagrad,
        "rmsprop": _rmsprop,
        "none": _none,
    }.get(n)
    if factory is None:
        raise ValueError(f"unknown updater {name!r}")
    return factory(hyper)


def updater_from_conf(conf) -> UpdaterDef:
    """Build from a NeuralNetConfiguration (maps the reference's builder
    hyperparameter names)."""
    return make_updater(
        conf.updater,
        learning_rate=conf.learning_rate,
        momentum=conf.momentum,
        rho=conf.rho,
        rms_decay=conf.rms_decay,
        beta1=conf.adam_mean_decay,
        beta2=conf.adam_var_decay,
        epsilon=conf.epsilon,
    )


# -- learning-rate schedules -------------------------------------------------

def schedule_lr(conf, iteration: int) -> float:
    """Host-side LR schedule (reference: LearningRatePolicy application in
    BaseOptimizer / layer conf). Returns the lr for this iteration."""
    base = conf.learning_rate
    pol = conf.lr_policy
    if pol in (None, "none"):
        return base
    if pol == "schedule":
        sched = conf.lr_schedule or {}
        best = base
        for k in sorted(int(i) for i in sched):
            if iteration >= k:
                best = sched[str(k)]
        return best
    if pol == "exponential":
        return base * (conf.lr_policy_decay_rate ** iteration)
    if pol == "inverse":
        return base / (1.0 + conf.lr_policy_decay_rate * iteration) ** conf.lr_policy_power
    if pol == "poly":
        return base * (1.0 - iteration / max(conf.lr_policy_steps, 1.0)) ** conf.lr_policy_power
    if pol == "sigmoid":
        import math

        return base / (1.0 + math.exp(-conf.lr_policy_decay_rate * (iteration - conf.lr_policy_steps)))
    if pol == "step":
        return base * (conf.lr_policy_decay_rate ** (iteration // max(conf.lr_policy_steps, 1.0)))
    raise ValueError(f"unknown lr policy {pol!r}")


# -- gradient normalization --------------------------------------------------

def normalize_gradients(layer_grads, mode: str, threshold: float):
    """Gradient normalization/clipping applied per layer before the updater
    (reference: BaseMultiLayerUpdater.preApply :284-325). layer_grads is a
    list of per-layer dicts."""
    if mode in (None, "none"):
        return layer_grads

    def _l2(tree):
        leaves = jax.tree_util.tree_leaves(tree)
        return jnp.sqrt(sum(jnp.sum(g * g) for g in leaves) + 1e-12)

    out = []
    for g in layer_grads:
        if not g:
            out.append(g)
            continue
        if mode == "renormalize_l2_per_layer":
            n = _l2(g)
            out.append(jax.tree_util.tree_map(lambda x: x / n, g))
        elif mode == "renormalize_l2_per_param_type":
            out.append({k: v / jnp.sqrt(jnp.sum(v * v) + 1e-12) for k, v in g.items()})
        elif mode == "clip_elementwise_absolute_value":
            out.append(jax.tree_util.tree_map(
                lambda x: jnp.clip(x, -threshold, threshold), g))
        elif mode == "clip_l2_per_layer":
            n = _l2(g)
            scale = jnp.minimum(1.0, threshold / n)
            out.append(jax.tree_util.tree_map(lambda x: x * scale, g))
        elif mode == "clip_l2_per_param_type":
            new = {}
            for k, v in g.items():
                n = jnp.sqrt(jnp.sum(v * v) + 1e-12)
                new[k] = v * jnp.minimum(1.0, threshold / n)
            out.append(new)
        else:
            raise ValueError(f"unknown gradient normalization {mode!r}")
    return out
