"""Vantage-point tree for exact k-NN (reference:
clustering/vptree/VPTree.java:224-251 search(target, k, results,
distances); 'invert' flag flips similarity functions to rank descending).

TPU-first redesign: the reference recurses point-at-a-time; here the tree
is a host-side index structure over numpy data, but every distance
evaluation is batched — construction partitions with one
vectorized distance column per node, and search walks the tree with
branch-and-bound while scoring whole leaves as one [q, leaf] block. For
small point sets a flat brute-force device matmul beats any tree; VPTree
picks that path automatically below ``brute_force_threshold``.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.clustering.distances import is_similarity, pairwise


def _np_dist(x: np.ndarray, y: np.ndarray, distance: str) -> np.ndarray:
    """Host-side [n] distances of points x to a single point y."""
    if distance in ("euclidean", "sqeuclidean"):
        d2 = np.maximum(((x - y[None, :]) ** 2).sum(axis=1), 0.0)
        return np.sqrt(d2) if distance == "euclidean" else d2
    if distance == "manhattan":
        return np.abs(x - y[None, :]).sum(axis=1)
    if distance == "cosinesimilarity":
        xn = x / (np.linalg.norm(x, axis=1, keepdims=True) + 1e-12)
        yn = y / (np.linalg.norm(y) + 1e-12)
        return xn @ yn
    if distance == "dot":
        return x @ y
    raise ValueError(f"unknown distance {distance!r}")


class _Node:
    __slots__ = ("vp_index", "radius", "inside", "outside", "leaf_indices")

    def __init__(self):
        self.vp_index: int = -1
        self.radius: float = 0.0
        self.inside: Optional["_Node"] = None
        self.outside: Optional["_Node"] = None
        self.leaf_indices: Optional[np.ndarray] = None


class VPTree:
    """VPTree(points, similarity_function='euclidean', invert=False).

    ``search(target, k)`` returns (indices, distances) of the k nearest
    (or most similar, for similarity functions / invert=True) points.
    """

    def __init__(self, points: np.ndarray,
                 similarity_function: str = "euclidean",
                 invert: bool = False, leaf_size: int = 64,
                 brute_force_threshold: int = 2048, seed: int = 0):
        self.points = np.asarray(points, np.float32)
        self.distance = similarity_function
        # similarity functions rank descending; invert flips explicitly
        self.descending = is_similarity(similarity_function) ^ bool(invert)
        self.leaf_size = int(leaf_size)
        self.brute = self.points.shape[0] <= int(brute_force_threshold)
        self._rng = np.random.default_rng(seed)
        # metric-tree pruning is only valid for true metrics
        self._prunable = similarity_function in (
            "euclidean", "manhattan") and not invert
        self.root = None
        if not self.brute:
            self.root = self._build(np.arange(self.points.shape[0]))

    # -- construction -------------------------------------------------------

    def _build(self, idx: np.ndarray) -> Optional[_Node]:
        if idx.size == 0:
            return None
        node = _Node()
        if idx.size <= self.leaf_size or not self._prunable:
            node.leaf_indices = idx
            return node
        vp_pos = int(self._rng.integers(0, idx.size))
        vp = idx[vp_pos]
        rest = np.delete(idx, vp_pos)
        d = _np_dist(self.points[rest], self.points[vp], self.distance)
        node.vp_index = int(vp)
        node.radius = float(np.median(d))
        inside = rest[d <= node.radius]
        outside = rest[d > node.radius]
        if inside.size == 0 or outside.size == 0:  # degenerate split
            node.leaf_indices = rest
            return node
        node.inside = self._build(inside)
        node.outside = self._build(outside)
        return node

    # -- search -------------------------------------------------------------

    def search(self, target: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
        target = np.asarray(target, np.float32).reshape(-1)
        k = min(int(k), self.points.shape[0])
        if self.brute or not self._prunable:
            # flat device path, ranked by self.descending (invert honored)
            d = pairwise(jnp.asarray(target)[None, :],
                         jnp.asarray(self.points), self.distance)
            if self.descending:
                vals, idx = jax.lax.top_k(d, k)
            else:
                vals, idx = jax.lax.top_k(-d, k)
                vals = -vals
            return np.asarray(idx)[0], np.asarray(vals)[0]
        # branch-and-bound over the metric tree; max-heap of the current
        # k best (negated distances)
        heap: List[Tuple[float, int]] = []

        def consider(indices: np.ndarray):
            d = _np_dist(self.points[indices], target, self.distance)
            for i, di in zip(indices, d):
                if len(heap) < k:
                    heapq.heappush(heap, (-float(di), int(i)))
                elif -heap[0][0] > di:
                    heapq.heapreplace(heap, (-float(di), int(i)))

        def tau() -> float:
            return -heap[0][0] if len(heap) == k else np.inf

        def walk(node: Optional[_Node]):
            if node is None:
                return
            if node.leaf_indices is not None:
                consider(node.leaf_indices)
                if node.vp_index >= 0:
                    consider(np.array([node.vp_index]))
                return
            dvp = float(_np_dist(self.points[node.vp_index][None, :],
                                 target, self.distance)[0])
            consider(np.array([node.vp_index]))
            if dvp <= node.radius:
                walk(node.inside)
                if dvp + tau() > node.radius:
                    walk(node.outside)
            else:
                walk(node.outside)
                if dvp - tau() <= node.radius:
                    walk(node.inside)

        walk(self.root)
        out = sorted((-nd, i) for nd, i in heap)
        idx = np.array([i for _, i in out])
        dist = np.array([d for d, _ in out])
        return idx, dist
