"""Host-side span tracing — the Dapper-style request/step half of the
observability layer (counters live in utils/metrics.py).

A span is a named, timed section of host code with a thread-local parent
stack, so `span("fit/step")` containing `span("fit/device_sync")` nests
the way Dapper trees do. Completed spans land in a bounded ring buffer
(old traffic ages out; a serving process never grows without bound) and
export two ways:

* JSONL — one span per line, newest last (`InferenceServer GET /trace`,
  `TracingListener(jsonl_path=...)`); greppable, tail-able.
* Chrome trace event JSON — load the dict from `to_chrome_trace()` into
  chrome://tracing / Perfetto and the host timeline sits next to the
  device xplane timeline captured by utils/profiler.py.

Device correlation: when enabled, each span also enters
`jax.profiler.TraceAnnotation(name)`, so the SAME names show up inside a
`jax.profiler.trace()` capture — `cli profile` op tables and host spans
line up by name.

Overhead contract: tracing is OFF by default and `span()` on the
disabled path returns a shared no-op context manager after one flag
check — no allocation, no lock, no clock read. The fit loop's phase
timers depend on this (ISSUE acceptance: ≤2% step-time regression with
tracing disabled).
"""

from __future__ import annotations

import json
import itertools
import threading
import time
from collections import deque
from typing import List, Optional

_counter = itertools.count(1)
_tls = threading.local()


class _NullSpan:
    """Shared disabled-path context manager: truthy checks, enter/exit
    no-ops, one instance for the whole process."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("tracer", "name", "args", "id", "parent", "t0", "_ann")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict]):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.id = next(_counter)
        self.parent = None
        self.t0 = 0.0
        self._ann = None

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self.parent = stack[-1].id if stack else None
        stack.append(self)
        if self.tracer.annotate_device:
            ann = _trace_annotation(self.name)
            if ann is not None:
                self._ann = ann
                ann.__enter__()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        self.tracer._record(self.name, self.t0, t1 - self.t0, self.id,
                            self.parent, self.args)
        return False


def _trace_annotation(name: str):
    """jax.profiler.TraceAnnotation(name) or None when jax (or the
    profiler module) is unavailable — tracing must work in a stub
    environment."""
    try:
        from jax.profiler import TraceAnnotation
    except Exception:
        return None
    try:
        return TraceAnnotation(name)
    except Exception:
        return None


class Tracer:
    """Bounded ring buffer of completed spans + the enable switch."""

    def __init__(self, capacity: int = 8192, annotate_device: bool = True):
        self.enabled = False
        self.annotate_device = annotate_device
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(capacity))
        # perf_counter origin so exported timestamps are relative to
        # tracer creation (chrome trace wants microseconds, any epoch)
        self._epoch = time.perf_counter()

    # -- recording -----------------------------------------------------------

    def span(self, name: str, **args):
        """Context manager timing a section. Disabled -> shared no-op."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, args or None)

    def instant(self, name: str, **args):
        """Zero-duration marker event (compile-cache insertions, helper
        auto-disables, ...)."""
        if not self.enabled:
            return
        stack = getattr(_tls, "stack", None)
        parent = stack[-1].id if stack else None
        self._record(name, time.perf_counter(), 0.0, next(_counter),
                     parent, args or None, phase="i")

    def _record(self, name, t0, dur, span_id, parent, args, phase="X"):
        ev = {
            "name": name,
            "ph": phase,
            "ts": round((t0 - self._epoch) * 1e6, 3),  # microseconds
            "dur": round(dur * 1e6, 3),
            "id": span_id,
            "parent": parent,
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    # -- readout -------------------------------------------------------------

    def recent(self, n: Optional[int] = None) -> List[dict]:
        """The n newest events (all when n is None, none when n <= 0 —
        a negative slice must never invert into 'everything BUT the
        newest n')."""
        with self._lock:
            evs = list(self._events)
        if n is None:
            return evs
        n = int(n)
        return evs[-n:] if n > 0 else []

    def clear(self):
        with self._lock:
            self._events.clear()

    def to_jsonl(self, n: Optional[int] = None) -> str:
        return "\n".join(json.dumps(ev) for ev in self.recent(n)) + "\n"

    def to_chrome_trace(self) -> dict:
        """chrome://tracing / Perfetto "trace event format" document."""
        events = []
        for ev in self.recent():
            ce = {
                "name": ev["name"],
                "ph": ev["ph"],
                "ts": ev["ts"],
                "pid": 1,
                "tid": ev["tid"],
            }
            if ev["ph"] == "X":
                ce["dur"] = ev["dur"]
            else:
                ce["s"] = "t"  # instant scope: thread
            args = dict(ev.get("args") or {})
            args["span_id"] = ev["id"]
            if ev.get("parent") is not None:
                args["parent_span_id"] = ev["parent"]
            ce["args"] = args
            events.append(ce)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path

    def write_jsonl(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.to_jsonl())
        return path


# -- the process-global tracer ------------------------------------------------

_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def enable(flag: bool = True):
    """Turn span recording on/off process-wide."""
    _TRACER.enabled = bool(flag)


def is_enabled() -> bool:
    return _TRACER.enabled


def span(name: str, **args):
    """Module-level shortcut: `with tracing.span("fit/step"): ...`."""
    if not _TRACER.enabled:
        return NULL_SPAN
    return _TRACER.span(name, **args)


def instant(name: str, **args):
    _TRACER.instant(name, **args)
