"""Network-level configuration: global hyperparameters + the fluent builder.

Analog of the reference's NeuralNetConfiguration.Builder (1,189 LoC fluent
DSL — nn/conf/NeuralNetConfiguration.java:517-735) and
MultiLayerConfiguration (549 LoC — backprop/pretrain flags, TBPTT, input
type, preprocessor map). Global hyperparameters set on the builder are
inherited by every layer whose own field is None, exactly the reference's
clone-defaults-into-layer behavior.

Workspace modes (NONE/SINGLE/SEPARATE) have no analog here: XLA owns all
intermediate buffers inside the compiled step, which is the TPU answer to
the reference's workspace memory management.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalFlatInput,
    ConvolutionalInput,
    FeedForwardInput,
    RecurrentInput,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
    CnnToRnnPreProcessor,
    FeedForwardToRnnPreProcessor,
    FlatToCnnPreProcessor,
    RnnToFeedForwardPreProcessor,
)
from deeplearning4j_tpu.nn.conf.serde import (
    config_from_dict,
    config_to_dict,
    register_config,
)


class Updater:
    """Mirrors nn/conf/Updater.java:11-14."""

    SGD = "sgd"
    ADAM = "adam"
    ADAMAX = "adamax"
    ADADELTA = "adadelta"
    NESTEROVS = "nesterovs"
    ADAGRAD = "adagrad"
    RMSPROP = "rmsprop"
    NONE = "none"


class GradientNormalization:
    """Mirrors nn/conf/GradientNormalization.java."""

    NONE = "none"
    RENORMALIZE_L2_PER_LAYER = "renormalize_l2_per_layer"
    RENORMALIZE_L2_PER_PARAM_TYPE = "renormalize_l2_per_param_type"
    CLIP_ELEMENTWISE_ABSOLUTE_VALUE = "clip_elementwise_absolute_value"
    CLIP_L2_PER_LAYER = "clip_l2_per_layer"
    CLIP_L2_PER_PARAM_TYPE = "clip_l2_per_param_type"


class BackpropType:
    STANDARD = "standard"
    TRUNCATED_BPTT = "tbptt"


class OptimizationAlgorithm:
    """Mirrors nn/api/OptimizationAlgorithm. SGD is the jitted fast path;
    the line-search family exists for parity and runs the same compiled
    gradient function inside a host-side search loop."""

    STOCHASTIC_GRADIENT_DESCENT = "sgd"
    LINE_GRADIENT_DESCENT = "line_gradient_descent"
    CONJUGATE_GRADIENT = "conjugate_gradient"
    LBFGS = "lbfgs"


class LearningRatePolicy:
    """Mirrors nn/conf/LearningRatePolicy (None/Exponential/Inverse/Poly/
    Sigmoid/Step/Schedule/Score-based decay)."""

    NONE = "none"
    EXPONENTIAL = "exponential"
    INVERSE = "inverse"
    POLY = "poly"
    SIGMOID = "sigmoid"
    STEP = "step"
    SCHEDULE = "schedule"


@register_config("net_conf")
@dataclasses.dataclass(kw_only=True)
class NeuralNetConfiguration:
    """Global (network-default) hyperparameters."""

    seed: int = 123
    optimization_algo: str = OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT
    activation: str = "sigmoid"
    weight_init: str = "xavier"
    dist: Optional[dict] = None
    bias_init: float = 0.0
    learning_rate: float = 1e-1
    bias_learning_rate: Optional[float] = None
    lr_policy: str = LearningRatePolicy.NONE
    lr_policy_decay_rate: float = 0.0
    lr_policy_steps: float = 1.0
    lr_policy_power: float = 1.0
    lr_schedule: Optional[Dict[str, float]] = None  # iteration -> lr
    updater: str = Updater.SGD
    momentum: float = 0.9
    rho: float = 0.95
    rms_decay: float = 0.95
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    epsilon: float = 1e-8
    l1: float = 0.0
    l2: float = 0.0
    dropout: float = 0.0
    gradient_normalization: str = GradientNormalization.NONE
    gradient_normalization_threshold: float = 1.0
    minimize: bool = True
    mini_batch: bool = True
    precision: str = "f32"

    @staticmethod
    def builder() -> "Builder":
        return Builder()


_INHERITED_FIELDS = ("activation", "weight_init", "dist", "bias_init", "l1", "l2")


def _apply_defaults(layer: L.LayerConf, conf: NeuralNetConfiguration) -> None:
    if isinstance(layer, L.FrozenLayer) and layer.inner is not None:
        _apply_defaults(layer.inner, conf)
        return
    if isinstance(layer, L.BaseLayerConf):
        for f in _INHERITED_FIELDS:
            if getattr(layer, f, None) is None:
                setattr(layer, f, getattr(conf, f))
    if layer.dropout is None:
        layer.dropout = conf.dropout


def _needs(layer: L.LayerConf) -> str:
    """Which input family a layer consumes: 'cnn', 'rnn', 'ff' or 'any'."""
    inner = layer.inner if isinstance(layer, L.FrozenLayer) else layer
    if isinstance(inner, (L.ConvolutionLayer, L.SubsamplingLayer, L.ZeroPaddingLayer,
                          L.LocalResponseNormalization)):
        return "cnn"
    if isinstance(inner, (L.LSTM, L.GravesLSTM, L.GravesBidirectionalLSTM,
                          L.RnnOutputLayer, L.Convolution1DLayer, L.Subsampling1DLayer)):
        return "rnn"
    if isinstance(inner, (L.DenseLayer, L.OutputLayer, L.CenterLossOutputLayer,
                          L.EmbeddingLayer, L.AutoEncoder,
                          L.VariationalAutoencoder)):
        return "ff"
    return "any"


def auto_preprocessor(it, layer: L.LayerConf):
    """Insert the shape adapter the reference's InputType.getPreProcessorForInputType
    would (MultiLayerConfiguration.Builder.setInputType)."""
    need = _needs(layer)
    if isinstance(it, ConvolutionalFlatInput):
        if need == "cnn":
            return FlatToCnnPreProcessor(height=it.height, width=it.width, channels=it.channels)
        return None  # dense layers eat the flat rows directly
    if isinstance(it, ConvolutionalInput):
        if need == "ff":
            return CnnToFeedForwardPreProcessor(height=it.height, width=it.width, channels=it.channels)
        if need == "rnn":
            return CnnToRnnPreProcessor()
    if isinstance(it, RecurrentInput):
        if need == "ff":
            return RnnToFeedForwardPreProcessor()
    if isinstance(it, FeedForwardInput):
        if need == "rnn":
            return FeedForwardToRnnPreProcessor()
        if need == "cnn":
            raise ValueError(
                "feed-forward input into a convolutional layer: set an "
                "InputType.convolutional(...) or add an explicit preprocessor"
            )
    return None


@register_config("multilayer_conf")
@dataclasses.dataclass(kw_only=True)
class MultiLayerConfiguration:
    """Sequential network configuration (reference:
    nn/conf/MultiLayerConfiguration.java)."""

    net_conf: NeuralNetConfiguration = dataclasses.field(default_factory=NeuralNetConfiguration)
    layers: List[L.LayerConf] = dataclasses.field(default_factory=list)
    # str(layer_index) -> preprocessor applied to that layer's input
    # (string keys so the JSON round trip is loss-free)
    preprocessors: Dict[str, object] = dataclasses.field(default_factory=dict)
    backprop_type: str = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20
    pretrain: bool = False
    input_type: Optional[object] = None

    # -- serde ---------------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(config_to_dict(self), indent=2)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        obj = config_from_dict(json.loads(s))
        if not isinstance(obj, MultiLayerConfiguration):
            raise ValueError("JSON does not describe a MultiLayerConfiguration")
        return obj

    def to_yaml(self) -> str:
        """reference: MultiLayerConfiguration.toYaml()."""
        from deeplearning4j_tpu.nn.conf.serde import config_to_yaml

        return config_to_yaml(self)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        from deeplearning4j_tpu.nn.conf.serde import config_from_yaml

        return config_from_yaml(s)

    # -- shape inference -----------------------------------------------------
    def input_types_per_layer(self):
        """List of the InputType flowing *into* each layer (after its
        preprocessor)."""
        it = self.input_type
        out = []
        for i, layer in enumerate(self.layers):
            pp = self.preprocessors.get(str(i))
            if pp is not None and it is not None:
                it = pp.output_type(it)
            out.append(it)
            if it is not None:
                it = layer.output_type(it)
        return out


class ListBuilder:
    """Builder for the layer list (reference:
    NeuralNetConfiguration.ListBuilder)."""

    def __init__(self, net_conf: NeuralNetConfiguration):
        self._conf = net_conf
        self._layers: List[L.LayerConf] = []
        self._preprocessors: Dict[str, object] = {}
        self._backprop_type = BackpropType.STANDARD
        self._tbptt_fwd = 20
        self._tbptt_bwd = 20
        self._pretrain = False
        self._input_type = None

    def layer(self, layer_conf: L.LayerConf) -> "ListBuilder":
        self._layers.append(layer_conf)
        return self

    def input_pre_processor(self, index: int, pp) -> "ListBuilder":
        self._preprocessors[str(index)] = pp
        return self

    def backprop_type(self, t: str) -> "ListBuilder":
        self._backprop_type = t
        return self

    def t_bptt_lengths(self, fwd: int, bwd: Optional[int] = None) -> "ListBuilder":
        self._tbptt_fwd = fwd
        self._tbptt_bwd = bwd if bwd is not None else fwd
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = flag
        return self

    def set_input_type(self, it) -> "ListBuilder":
        self._input_type = it
        return self

    def build(self) -> MultiLayerConfiguration:
        for lc in self._layers:
            _apply_defaults(lc, self._conf)
        # Shape inference + automatic preprocessor insertion
        it = self._input_type
        if it is not None:
            for i, layer in enumerate(self._layers):
                if str(i) not in self._preprocessors:
                    pp = auto_preprocessor(it, layer)
                    if pp is not None:
                        self._preprocessors[str(i)] = pp
                if str(i) in self._preprocessors:
                    it = self._preprocessors[str(i)].output_type(it)
                layer.infer_n_in(it)
                it = layer.output_type(it)
        else:
            # without an InputType, wire n_in from the previous layer's n_out
            prev = None
            for layer in self._layers:
                inner = layer.inner if isinstance(layer, L.FrozenLayer) else layer
                if isinstance(inner, L.FeedForwardLayerConf) and inner.n_in is None and prev is not None:
                    inner.n_in = prev
                if isinstance(inner, L.FeedForwardLayerConf):
                    prev = inner.n_out
        return MultiLayerConfiguration(
            net_conf=self._conf,
            layers=self._layers,
            preprocessors=self._preprocessors,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_bwd_length=self._tbptt_bwd,
            pretrain=self._pretrain,
            input_type=self._input_type,
        )


class Builder:
    """Fluent global-hyperparameter builder (reference:
    NeuralNetConfiguration.Builder). Each setter mirrors a reference method;
    snake_case but same vocabulary."""

    def __init__(self):
        self._kw = {}

    def _set(self, **kw) -> "Builder":
        self._kw.update(kw)
        return self

    def seed(self, s: int) -> "Builder":
        return self._set(seed=int(s))

    def optimization_algo(self, algo: str) -> "Builder":
        return self._set(optimization_algo=algo)

    def activation(self, a: str) -> "Builder":
        return self._set(activation=a)

    def weight_init(self, w: str) -> "Builder":
        return self._set(weight_init=w)

    def dist(self, d: dict) -> "Builder":
        return self._set(dist=d, weight_init="distribution")

    def bias_init(self, b: float) -> "Builder":
        return self._set(bias_init=b)

    def learning_rate(self, lr: float) -> "Builder":
        return self._set(learning_rate=lr)

    def bias_learning_rate(self, lr: float) -> "Builder":
        return self._set(bias_learning_rate=lr)

    def learning_rate_policy(self, p: str) -> "Builder":
        return self._set(lr_policy=p)

    def lr_policy_decay_rate(self, r: float) -> "Builder":
        return self._set(lr_policy_decay_rate=r)

    def lr_policy_steps(self, s: float) -> "Builder":
        return self._set(lr_policy_steps=s)

    def lr_policy_power(self, p: float) -> "Builder":
        return self._set(lr_policy_power=p)

    def learning_rate_schedule(self, sched: Dict[int, float]) -> "Builder":
        return self._set(
            lr_schedule={str(k): float(v) for k, v in sched.items()},
            lr_policy=LearningRatePolicy.SCHEDULE,
        )

    def updater(self, u: str) -> "Builder":
        return self._set(updater=u)

    def momentum(self, m: float) -> "Builder":
        return self._set(momentum=m)

    def rho(self, r: float) -> "Builder":
        return self._set(rho=r)

    def rms_decay(self, r: float) -> "Builder":
        return self._set(rms_decay=r)

    def adam_mean_decay(self, b1: float) -> "Builder":
        return self._set(adam_mean_decay=b1)

    def adam_var_decay(self, b2: float) -> "Builder":
        return self._set(adam_var_decay=b2)

    def epsilon(self, e: float) -> "Builder":
        return self._set(epsilon=e)

    def l1(self, v: float) -> "Builder":
        return self._set(l1=v)

    def l2(self, v: float) -> "Builder":
        return self._set(l2=v)

    def dropout(self, d: float) -> "Builder":
        return self._set(dropout=d)

    def gradient_normalization(self, g: str) -> "Builder":
        return self._set(gradient_normalization=g)

    def gradient_normalization_threshold(self, t: float) -> "Builder":
        return self._set(gradient_normalization_threshold=t)

    def minimize(self, m: bool) -> "Builder":
        return self._set(minimize=m)

    def mini_batch(self, m: bool) -> "Builder":
        return self._set(mini_batch=m)

    def precision(self, p: str) -> "Builder":
        return self._set(precision=p)

    def build(self) -> NeuralNetConfiguration:
        return NeuralNetConfiguration(**self._kw)

    def list(self) -> ListBuilder:
        return ListBuilder(self.build())

    def graph_builder(self):
        """DAG configuration builder (reference:
        NeuralNetConfiguration.Builder.graphBuilder())."""
        from deeplearning4j_tpu.nn.conf.graph import GraphBuilder

        return GraphBuilder(self.build())
