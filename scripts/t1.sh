#!/usr/bin/env bash
# Tier-1 verify — the exact command from ROADMAP.md, wrapped so builders
# and CI invoke ONE entrypoint instead of each re-typing (and drifting
# from) the canonical flags. Prints DOTS_PASSED=<n> after the run.
#
# Gate semantics: the exit status reports REGRESSIONS, not raw failures.
# The growth seed ships 35 pre-existing failures; a raw count (or
# pytest's exit code) cannot distinguish new breakage from inherited
# breakage. So the failing-test NAMES are recorded to an artifact
# ($T1_FAILURES_ARTIFACT, default /tmp/_t1_failures.txt) and diffed
# against the committed baseline tests/tier1_baseline_failures.txt:
#   exit 0  — no failing test that is not already in the baseline
#   exit 1  — new failures (they are listed)
#   exit >1 — pytest itself died (timeout, internal error, interrupt)
# Slow-marked tests (serving load, multi-process) are excluded — that is
# what keeps tier-1 fast.
set -o pipefail
cd "$(dirname "$0")/.."

# -- static-analysis gate ----------------------------------------------------
# Concurrency/robustness lint (analysis/lint.py: bare except, timeout-less
# queue ops, unnamed/non-daemon threads, lock-order cycles, stray print)
# diffed against the committed scripts/lint_baseline.txt. This subsumes
# the old inline print-grep guard (print is finding code CC006).
bash scripts/lint.sh || exit 1

# -- 2-simulated-device sharding smoke ---------------------------------------
# The mainline multi-chip fit() path — auto-attached mesh, in-graph
# gradient all-reduce, sharded == single-device numerics — exercised
# under a forced 2-device CPU platform with the PRODUCTION default
# DL4J_AUTO_MESH=1 (the main suite below runs with auto-mesh off so its
# hundreds of single-device fits don't each compile an 8-way SPMD
# program). A separate interpreter because the device count is fixed at
# backend init. DL4J_GRAD_BUCKET_BYTES=512 forces the smoke nets
# (~1 KB of grads — far under the 4 MiB default, which would collapse
# them to one bucket) to split into >1 gradient bucket, so the BUCKETED
# reduce path is what this smoke exercises, not the degenerate
# one-bucket schedule.
rm -f /tmp/_t1_sharding.log
if timeout -k 10 240 env JAX_PLATFORMS=cpu DL4J_AUTO_MESH=1 \
    DL4J_GRAD_BUCKET_BYTES=512 \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python -m pytest tests/test_sharded_step.py -q -m 'not slow' -k smoke \
    -p no:cacheprovider > /tmp/_t1_sharding.log 2>&1; then
    echo "T1 SHARDING SMOKE: ok (2 simulated devices, auto-mesh fit)"
else
    echo "T1 SHARDING SMOKE: FAILED — tail of /tmp/_t1_sharding.log:"
    tail -20 /tmp/_t1_sharding.log
    exit 1
fi

# -- decode-engine smoke ------------------------------------------------------
# The continuous-batching autoregressive tier (serving/decode.py): a tiny
# charlstm engine with 4 slots and 2 weighted tenants serves mixed
# prompts through one live weight swap — asserting per-tenant book
# conservation AND a constant program cache after warmup (zero retraces
# across admissions and the swap: the O(1)-compile contract).
rm -f /tmp/_t1_decode.log
if timeout -k 10 180 env JAX_PLATFORMS=cpu \
    python -m deeplearning4j_tpu.serving.decode --smoke \
    > /tmp/_t1_decode.log 2>&1; then
    echo "T1 DECODE SMOKE: ok (4 slots, 2 tenants, 1 weight swap, zero retraces)"
else
    echo "T1 DECODE SMOKE: FAILED — tail of /tmp/_t1_decode.log:"
    tail -20 /tmp/_t1_decode.log
    exit 1
fi

# -- tenant-books smoke --------------------------------------------------------
# The cross-tier chip-budget ledger (utils/resourcemeter + utils/tenancy):
# two tenants through the decode smoke plus one metered fit in its own
# interpreter, asserting per-tenant device-seconds sum to the process
# total per tier (spend conservation), the outcome books balance, and
# `cli tenants` renders the in-process view with exit 0.
rm -f /tmp/_t1_tenants.log
if timeout -k 10 240 env JAX_PLATFORMS=cpu \
    python -m deeplearning4j_tpu.utils.resourcemeter --smoke \
    > /tmp/_t1_tenants.log 2>&1; then
    echo "T1 TENANT BOOKS: ok (decode tenants + metered fit, cross-tier conservation)"
else
    echo "T1 TENANT BOOKS: FAILED — tail of /tmp/_t1_tenants.log:"
    tail -20 /tmp/_t1_tenants.log
    exit 1
fi

# -- recsys sparse-pipeline smoke ---------------------------------------------
# The sparse-embedding tier (parallel/sparse over the sharded
# paramserver): tiny table, 2 in-process endpoints, zipf ids, a few
# pipelined steps — asserting the cache books conserve (pull_rows ==
# cache_hit + cache_miss), the prefetch-on trajectory is byte-identical
# to the synchronous one (cache + prefetch are transparent), and zero
# dl4j-sparse-* threads survive close().
rm -f /tmp/_t1_recsys.log
if timeout -k 10 180 env JAX_PLATFORMS=cpu \
    python -m deeplearning4j_tpu.parallel.sparse --smoke \
    > /tmp/_t1_recsys.log 2>&1; then
    echo "T1 RECSYS SMOKE: ok (2 endpoints, zipf ids, books conserve, prefetch == sync)"
else
    echo "T1 RECSYS SMOKE: FAILED — tail of /tmp/_t1_recsys.log:"
    tail -20 /tmp/_t1_recsys.log
    exit 1
fi

# -- lock-order sanitizer smoke -----------------------------------------------
# The concurrency audit (utils/locktrace + analysis/concurrency_audit):
# serving + decode + sparse/paramserver run with DL4J_LOCKCHECK armed,
# their witnessed lock-acquisition orders merged with the lexical lock
# graph, and ALL CN001/CN002/CN003 finding names diffed against the
# committed scripts/lock_baseline.txt (ideally empty). A new name means
# a lock-order cycle, a blocking call under a lock, or a jitted
# dispatch entered with a lock held crept into a mainline tier.
rm -f /tmp/_t1_lockaudit.log /tmp/_t1_lock_findings.txt
if timeout -k 10 420 env JAX_PLATFORMS=cpu DL4J_LOCKCHECK=1 \
    python -m deeplearning4j_tpu.analysis.concurrency_audit --smoke --quiet \
    --baseline scripts/lock_baseline.txt \
    --names-out /tmp/_t1_lock_findings.txt \
    > /tmp/_t1_lockaudit.log 2>&1; then
    echo "T1 LOCK AUDIT: ok ($(grep -a '^lock audit:' /tmp/_t1_lockaudit.log | tail -1))"
else
    echo "T1 LOCK AUDIT: FAILED — tail of /tmp/_t1_lockaudit.log:"
    tail -20 /tmp/_t1_lockaudit.log
    echo "T1 LOCK AUDIT: finding names artifact: /tmp/_t1_lock_findings.txt"
    exit 1
fi

# -- kernel-coverage smoke ----------------------------------------------------
# The 53/53 contract (analysis/kernelcoverage.py): every ResNet-50 conv
# instance must resolve to covered or declined-with-roofline-verdict in
# planning mode — a silently-unsupported shape is a kernel-family hole
# nobody decided on, and fails the gate. Pure config walking, no trace.
rm -f /tmp/_t1_kcov.log
if timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python -m deeplearning4j_tpu.analysis.kernelcoverage --preset resnet50 \
    > /tmp/_t1_kcov.log 2>&1; then
    echo "T1 KERNEL COVERAGE: ok ($(tail -1 /tmp/_t1_kcov.log))"
else
    echo "T1 KERNEL COVERAGE: FAILED — tail of /tmp/_t1_kcov.log:"
    tail -20 /tmp/_t1_kcov.log
    exit 1
fi

# -- the canonical tier-1 pytest run -----------------------------------------
# T1_METRICS_DUMP=1 makes tests/conftest.py write the shared metrics
# registry's snapshot after the session (T1_METRICS_ARTIFACT, default
# /tmp/_t1_metrics.json) — diff compile counts across PRs.
# T1_BLACKBOX_ARTIFACT arms the flight recorder's crash hooks
# (tests/conftest.py -> utils/blackbox.install_crash_hooks): a session
# the timeout kills leaves a dump naming the wedged thread — render it
# with `python -m deeplearning4j_tpu.cli blackbox <artifact>`.
blackbox="${T1_BLACKBOX_ARTIFACT:-/tmp/_t1_blackbox.json}"
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu T1_BLACKBOX_ARTIFACT="$blackbox" python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

artifact="${T1_FAILURES_ARTIFACT:-/tmp/_t1_failures.txt}"
baseline="tests/tier1_baseline_failures.txt"
# FAILED lines carry "<id> - <reason>"; ERROR lines (collection errors)
# carry the file — both are regressions when not in the baseline. Strip
# the reason suffix rather than taking field 2: parametrized ids may
# contain spaces and a truncated id could mask a sibling-param regression.
grep -aE '^(FAILED|ERROR) ' /tmp/_t1.log \
    | sed -e 's/^FAILED //' -e 's/^ERROR //' -e 's/ - .*$//' \
    | sort -u > "$artifact"

if [ "$rc" -gt 1 ]; then
    echo "T1: pytest exited rc=$rc (timeout/internal error) — not gating on names"
    if [ -f "$blackbox" ]; then
        echo "T1 BLACKBOX: $blackbox (render: python -m deeplearning4j_tpu.cli blackbox $blackbox)"
        [ -f "$blackbox.stacks.txt" ] && echo "T1 BLACKBOX: native-level thread stacks: $blackbox.stacks.txt"
    else
        echo "T1 BLACKBOX: no artifact at $blackbox (session died before the hooks armed?)"
    fi
    # a wedged session's ledger still holds everything sampled up to the
    # kill — the metric trajectory INTO the failure
    if [ -n "${T1_LEDGER_DUMP:-}" ] && [ -f "${T1_LEDGER_ARTIFACT:-/tmp/_t1_ledger.jsonl}" ]; then
        echo "T1 LEDGER: ${T1_LEDGER_ARTIFACT:-/tmp/_t1_ledger.jsonl} (replay: python -m deeplearning4j_tpu.cli metrics --ledger ${T1_LEDGER_ARTIFACT:-/tmp/_t1_ledger.jsonl})"
    fi
    exit "$rc"
fi
new_failures=$(comm -13 <(sort -u "$baseline") "$artifact")
if [ -n "$new_failures" ]; then
    echo "T1 REGRESSIONS — failing tests not in $baseline:"
    echo "$new_failures"
    exit 1
fi
if [ -n "${T1_METRICS_DUMP:-}" ]; then
    echo "T1 metrics snapshot: ${T1_METRICS_ARTIFACT:-/tmp/_t1_metrics.json}"
fi
# T1_TRACE_DUMP=1 makes tests/conftest.py export the session's span ring
# as JSONL (T1_TRACE_ARTIFACT, default /tmp/_t1_trace.jsonl) — render
# with `python -m deeplearning4j_tpu.cli trace <artifact>`.
if [ -n "${T1_TRACE_DUMP:-}" ]; then
    echo "T1 trace dump: ${T1_TRACE_ARTIFACT:-/tmp/_t1_trace.jsonl}"
fi
# T1_LEDGER_DUMP=1 makes tests/conftest.py record the whole session's
# metrics-registry trajectory as a run-ledger artifact
# (T1_LEDGER_ARTIFACT, default /tmp/_t1_ledger.jsonl) — replay with
# `python -m deeplearning4j_tpu.cli metrics --ledger <artifact>`.
if [ -n "${T1_LEDGER_DUMP:-}" ]; then
    echo "T1 ledger dump: ${T1_LEDGER_ARTIFACT:-/tmp/_t1_ledger.jsonl}"
fi
# surface the conftest thread-leak guard's session verdict (each leak also
# failed its test above — this is the at-a-glance summary)
grep -a '^T1 THREAD GUARD:' /tmp/_t1.log || echo "T1 THREAD GUARD: no verdict line (session died early?)"
# checkpoint tmp-orphan guard: a *.tmp file surviving the session is a
# save that died between write and atomic rename (conftest scans the
# run's tmp dirs — same spirit as the thread-leak guard)
grep -a '^T1 CKPT TMP GUARD:' /tmp/_t1.log || echo "T1 CKPT TMP GUARD: no verdict line (session died early?)"
# perf snapshot: the static cost model's totals for the tiny preset
# (conftest recomputes per session) — accidental FLOP-model drift shows
# up here as a changed number, not as a silently re-based MFU claim
grep -a '^T1 PERF SNAPSHOT:' /tmp/_t1.log || echo "T1 PERF SNAPSHOT: no verdict line (session died early?)"
echo "T1 OK: $(wc -l < "$artifact" | tr -d ' ') failing (all within the $(wc -l < "$baseline" | tr -d ' ')-name baseline); artifact: $artifact"
exit 0
