"""Component health + hang watchdog — the liveness half of the crash
forensics layer (utils/blackbox.py is the black-box half).

PR 3's metrics report rates while a process is alive and making
progress; they say nothing when a step wedges, a pipeline worker blocks
on a queue nobody drains, or a serving dispatcher dies inside a device
forward. This module turns liveness into data:

* every long-running component registers a `Heartbeat` (fit loop,
  serving collector/dispatcher, device-prefetch and ETL workers, the
  paramserver push drain, the UI remote router). A thread marks itself
  *busy* while holding work (`with hb.busy(): ...`) and `beat()`s on
  progress; a thread waiting for work holds no busy slot, so an idle
  component is healthy by construction — only a thread that TOOK work
  and stopped advancing reads as a stall.
* a single `dl4j-watchdog` daemon thread scans every heartbeat: a busy
  slot older than `stall_after` flips the component to DEGRADED, older
  than `unhealthy_after` to UNHEALTHY, and recovery flips it back. Each
  transition updates the `component_health{component}` gauge (0 ok / 1
  degraded / 2 unhealthy), bumps `watchdog_stall_total{component}` on
  entry to a stall episode, appends to a bounded transition history
  (consumed by train/listeners.HealthTransitionListener and ui/stats),
  and hands the first degradation of an episode to the flight recorder
  for a forensic snapshot.
* `status()` is the aggregated health model serving's `GET /health`
  returns (503 when any component is unhealthy) — the hook load-shedding
  and replica eviction build on.

`net.fit(hang_timeout=...)` registers the fit heartbeat with an
`on_stall` action that dumps the flight recorder and raises
`StepHangError` (carrying the dump path) inside the fitting thread, so
a wedged step becomes a diagnosable exception instead of a silent hang.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.utils import metrics as _metrics

logger = logging.getLogger("deeplearning4j_tpu")

OK = "ok"
DEGRADED = "degraded"
UNHEALTHY = "unhealthy"
# status -> numeric severity: the component_health gauge value, and the
# numeric form storage codecs keep when string fields get dropped
LEVELS = {OK: 0, DEGRADED: 1, UNHEALTHY: 2}
_LEVEL = LEVELS

# watchdog scan cadence bounds: a quarter of the shortest registered
# stall interval, clamped so an idle registry costs nothing measurable
# and a millisecond-scale test interval cannot busy-spin the thread
_MIN_INTERVAL = 0.02
_MAX_INTERVAL = 5.0


class StepHangError(RuntimeError):
    """A fit step exceeded its `hang_timeout`. `dump_path` names the
    flight-recorder dump written at detection time (None when the dump
    itself failed) — the forensics, not just the fact of the hang."""

    def __init__(self, message: str = "", dump_path: Optional[str] = None):
        super().__init__(message or "fit step hang detected")
        self.dump_path = dump_path


def _async_raise(thread_ident: int, exc_type) -> bool:
    """Raise `exc_type` inside another thread at its next bytecode
    boundary (CPython PyThreadState_SetAsyncExc). A thread wedged in a
    C call only sees it when it returns to the interpreter — which is
    exactly the Python-level-wedge class (queue waits, iterator sleep
    loops) the hang_timeout contract targets. Returns False when the
    raise could not be delivered."""
    import ctypes

    try:
        res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_ident), ctypes.py_object(exc_type))
        if res > 1:  # delivered to >1 state: undo — interpreter invariant
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(thread_ident), None)
            return False
        return res == 1
    except Exception:  # non-CPython or restricted ctypes: degrade gracefully
        return False


class _BusySlot:
    """Context manager marking the current thread busy on a heartbeat."""
    __slots__ = ("hb",)

    def __init__(self, hb: "Heartbeat"):
        self.hb = hb

    def __enter__(self):
        hb = self.hb
        with hb._lock:
            hb._busy[threading.get_ident()] = time.monotonic()
        return hb

    def __exit__(self, *exc):
        hb = self.hb
        with hb._lock:
            hb._busy.pop(threading.get_ident(), None)
        return False


class Heartbeat:
    """One component's liveness record. Multiple threads may share one
    heartbeat (the multi-worker ETL stage): the component stalls when
    its OLDEST busy slot goes stale, so one wedged worker is not masked
    by its siblings' progress."""

    def __init__(self, name: str, stall_after: float,
                 unhealthy_after: Optional[float] = None,
                 on_stall: Optional[Callable[["Heartbeat", float], None]]
                 = None):
        self.name = name
        self.stall_after = float(stall_after)
        self.unhealthy_after = (float(unhealthy_after)
                                if unhealthy_after is not None
                                else 4.0 * self.stall_after)
        self.on_stall = on_stall
        self.state = OK  # watchdog-owned; scans mutate it
        # RLock: the crash-dump path (a signal handler on the main
        # thread) reads health status and may interrupt a beat() that
        # holds this lock on the same thread
        self._lock = threading.RLock()
        self._busy: Dict[int, float] = {}  # thread ident -> last activity
        self._stall_fired = False  # on_stall runs once per episode

    def has_busy_slots(self) -> bool:
        with self._lock:
            return bool(self._busy)

    def busy(self) -> _BusySlot:
        """`with hb.busy(): <work>` — the thread holds work; silence now
        counts as a stall. Cost: two dict ops and two clock reads."""
        return _BusySlot(self)

    def beat(self):
        """Progress mark: refresh this thread's busy slot (no-op for a
        thread that is not inside `busy()` — an idle component has
        nothing to prove)."""
        tid = threading.get_ident()
        with self._lock:
            if tid in self._busy:
                self._busy[tid] = time.monotonic()

    def check(self, now: Optional[float] = None):
        """(state, stalled_for_seconds, stalled_thread_idents) from the
        current busy slots. Pure — no side effects; the watchdog scan
        and `status()` both call this."""
        now = time.monotonic() if now is None else now
        with self._lock:
            slots = dict(self._busy)
        if not slots:
            return OK, 0.0, []
        age = now - min(slots.values())
        if age >= self.unhealthy_after:
            state = UNHEALTHY
        elif age >= self.stall_after:
            state = DEGRADED
        else:
            return OK, 0.0, []
        stale = [tid for tid, t in slots.items()
                 if now - t >= self.stall_after]
        return state, age, stale


def _thread_names(idents: List[int]) -> List[str]:
    by_ident = {t.ident: t.name for t in threading.enumerate()}
    return [by_ident.get(tid, f"ident-{tid}") for tid in idents]


class HealthRegistry:
    """Process-global component-health map + the one watchdog thread.

    The watchdog starts lazily on the first `register()` and lives for
    the process (daemon, named `dl4j-watchdog`); with every component
    healthy a scan is a handful of dict reads. Re-registering a name
    replaces the previous heartbeat (a restarted component starts a
    fresh episode); `unregister` only removes the heartbeat it is handed
    so a stale owner cannot evict its replacement."""

    def __init__(self):
        self._lock = threading.RLock()  # see Heartbeat._lock
        self._components: Dict[str, Heartbeat] = {}
        # externally-asserted conditions (analysis/slo firing rules mark
        # their owning component DEGRADED here): name -> {state, reason,
        # since}. Merged with the heartbeat view in status() — the worst
        # of the two wins — and cleared by set_condition(name, OK).
        self._conditions: Dict[str, dict] = {}
        self._transitions: deque = deque(maxlen=256)
        self._seq = 0
        self._listeners: List[Callable] = []
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = _metrics.get_registry()
        self._gauge = reg.gauge(
            "component_health",
            "watchdog view per component: 0 ok, 1 degraded, 2 unhealthy",
            ("component",))
        self._stalls = reg.counter(
            "watchdog_stall_total",
            "stall episodes the watchdog opened, per component",
            ("component",))

    # -- registration --------------------------------------------------------

    def register(self, name: str, stall_after: float = 60.0,
                 unhealthy_after: Optional[float] = None,
                 on_stall: Optional[Callable] = None) -> Heartbeat:
        with self._lock:
            # a name collision with a heartbeat whose threads are BUSY is
            # two live registrants (e.g. two concurrent fits): evicting
            # the first would silently disable its watchdog/hang_timeout,
            # so the newcomer gets a suffixed component name instead. A
            # collision with an idle heartbeat is the restart case —
            # replace, fresh episode.
            base, k = name, 1
            existing = self._components.get(name)
            while existing is not None and existing.has_busy_slots():
                k += 1
                name = f"{base}#{k}"
                existing = self._components.get(name)
            if name != base:
                logger.warning(
                    "health component %r already registered and active; "
                    "registering as %r", base, name)
            hb = Heartbeat(name, stall_after, unhealthy_after, on_stall)
            self._components[name] = hb
            started = self._thread is not None
        self._gauge.labels(name).set(0)
        if not started:
            self._start_watchdog()
        self._wake.set()  # pick up a possibly-shorter scan interval now
        return hb

    def unregister(self, hb: Heartbeat):
        with self._lock:
            if self._components.get(hb.name) is hb:
                del self._components[hb.name]
            else:
                return
        if hb.state != OK:  # leave no stuck gauge behind
            self._record_transition(hb, OK, 0.0, [])
        with self._lock:
            cond = self._conditions.get(hb.name)
        self._gauge.labels(hb.name).set(
            _LEVEL[cond["state"]] if cond else 0)

    # -- externally-asserted conditions ---------------------------------------

    def set_condition(self, component: str, state: str, reason: str = ""):
        """Assert a component's health from OUTSIDE the watchdog model —
        the hook SLO rules (analysis/slo via utils/runledger) use to mark
        an owning component DEGRADED while a rule fires, and to clear it
        on resolve (`state=OK`). Conditions merge with the busy-slot
        view: `status()` and the `component_health` gauge report the
        WORST of the heartbeat state and the asserted condition, so a
        firing latency rule degrades "serving" even while its pipeline
        threads are individually live. Each level change records a
        transition (kind="condition") through the same history/listener/
        flight-recorder path as watchdog transitions."""
        if state not in _LEVEL:
            raise ValueError(f"unknown health state {state!r}")
        with self._lock:
            prev = self._conditions.get(component)
            old = prev["state"] if prev else OK
            if state == old and prev is not None:
                prev["reason"] = reason  # refresh, no transition
                return
            if state == OK:
                self._conditions.pop(component, None)
                if prev is None:
                    return  # clearing a condition never asserted: no-op
            else:
                self._conditions[component] = {
                    "state": state, "reason": reason, "since": time.time()}
            self._seq += 1
            tr = {
                "seq": self._seq,
                "ts": time.time(),
                "component": component,
                "from": old,
                "to": state,
                "kind": "condition",
                "reason": reason,
            }
            self._transitions.append(tr)
            listeners = list(self._listeners)
            hb = self._components.get(component)
        # the gauge reports the MERGED level — a heartbeat-OK component
        # with a DEGRADED condition reads 1, and clearing the condition
        # falls back to the heartbeat's own state, not blindly to 0
        hb_level = _LEVEL[hb.state] if hb is not None else 0
        self._gauge.labels(component).set(max(hb_level, _LEVEL[state]))
        try:
            from deeplearning4j_tpu.utils import blackbox

            blackbox.get_recorder().record_event(
                "health_condition", component=component, frm=old, to=state,
                reason=reason)
        except Exception:
            logger.exception("flight-recorder condition event failed")
        for fn in listeners:
            try:
                fn(tr)
            except Exception:
                logger.exception("health transition listener failed")

    def get_condition(self, component: str) -> Optional[dict]:
        with self._lock:
            c = self._conditions.get(component)
            return dict(c) if c else None

    def add_listener(self, fn: Callable[[dict], None]):
        """`fn(transition_dict)` on every health transition — the hook
        train/listeners.HealthTransitionListener and tests use. A raising
        listener is logged and dropped for that event, never fatal."""
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(self, fn: Callable):
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    # -- readout -------------------------------------------------------------

    def status(self) -> dict:
        """The aggregated health model (serving `GET /health`): overall
        status is the worst component's, computed LIVE from the busy
        slots (not the last scan), so recovery is visible immediately."""
        with self._lock:
            comps = dict(self._components)
            conds = {k: dict(v) for k, v in self._conditions.items()}
        now = time.monotonic()
        out, worst = {}, OK
        for name, hb in sorted(comps.items()):
            state, age, stale = hb.check(now)
            detail = {"status": state,
                      "stall_after_seconds": hb.stall_after}
            if state != OK:
                detail["stalled_for_seconds"] = round(age, 3)
                detail["stalled_threads"] = _thread_names(stale)
            cond = conds.pop(name, None)
            if cond is not None:
                # an asserted condition (SLO rule firing) merges with the
                # heartbeat view: worst state wins, the condition detail
                # rides along so /health names the judging rule
                detail["condition"] = cond
                if _LEVEL[cond["state"]] > _LEVEL[state]:
                    state = cond["state"]
                    detail["status"] = state
            if _LEVEL[state] > _LEVEL[worst]:
                worst = state
            out[name] = detail
        for name, cond in sorted(conds.items()):
            # condition-only components (no heartbeat): still first-class
            state = cond["state"]
            out[name] = {"status": state, "condition": cond}
            if _LEVEL[state] > _LEVEL[worst]:
                worst = state
        return {"status": worst, "components": out}

    def transitions_since(self, seq: int = 0) -> List[dict]:
        """Transition records newer than `seq` (each carries its own
        monotonically-increasing "seq") — cursor-style consumption for
        listeners that poll."""
        with self._lock:
            return [t for t in self._transitions if t["seq"] > seq]

    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    # -- the watchdog --------------------------------------------------------

    def _start_watchdog(self):
        t = threading.Thread(target=self._watchdog_loop, daemon=True,
                             name="dl4j-watchdog")
        with self._lock:
            if self._thread is not None:
                return
            self._thread = t
        t.start()

    def _interval(self) -> float:
        with self._lock:
            stalls = [hb.stall_after for hb in self._components.values()]
        if not stalls:
            return _MAX_INTERVAL
        return min(_MAX_INTERVAL, max(_MIN_INTERVAL, min(stalls) / 4.0))

    def _watchdog_loop(self):
        while True:
            self._wake.wait(self._interval())
            self._wake.clear()
            try:
                self.scan()
            except Exception:  # a scan bug must not kill liveness forever
                logger.exception("watchdog scan failed")

    def scan(self, now: Optional[float] = None):
        """One watchdog pass (the thread's body, callable directly from
        tests): compute each component's state, record transitions, run
        stall actions."""
        now = time.monotonic() if now is None else now
        with self._lock:
            comps = list(self._components.values())
        for hb in comps:
            state, age, stale = hb.check(now)
            old = hb.state
            if state != old:
                hb.state = state
                self._record_transition(hb, state, age, stale, old=old)
            if state == OK:
                hb._stall_fired = False
            elif not hb._stall_fired:
                hb._stall_fired = True
                self._on_first_stall(hb, age, stale)

    def _on_first_stall(self, hb: Heartbeat, age: float, stale: List[int]):
        """Entry into a stall episode: counter, flight-recorder snapshot,
        then the component's own action (e.g. the fit hang raiser)."""
        self._stalls.labels(hb.name).inc()
        names = _thread_names(stale)
        logger.warning("watchdog: component %r stalled for %.3fs "
                       "(threads: %s)", hb.name, age, names)
        try:
            from deeplearning4j_tpu.utils import blackbox

            blackbox.get_recorder().on_degradation(hb.name, age, names)
        except Exception:
            logger.exception("flight-recorder degradation snapshot failed")
        if hb.on_stall is not None:
            try:
                hb.on_stall(hb, age)
            except Exception:
                logger.exception("on_stall action for %r failed", hb.name)

    def _record_transition(self, hb: Heartbeat, state: str, age: float,
                           stale: List[int], old: Optional[str] = None):
        with self._lock:
            self._seq += 1
            tr = {
                "seq": self._seq,
                "ts": time.time(),
                "component": hb.name,
                "from": old if old is not None else hb.state,
                "to": state,
                "stalled_for_seconds": round(age, 3),
                "stalled_threads": _thread_names(stale),
            }
            self._transitions.append(tr)
            listeners = list(self._listeners)
            cond = self._conditions.get(hb.name)
        # merged with any asserted condition: a watchdog recovery must
        # not zero the gauge while an SLO rule still holds the component
        # DEGRADED (and vice versa — see set_condition)
        cond_level = _LEVEL[cond["state"]] if cond else 0
        self._gauge.labels(hb.name).set(max(_LEVEL[state], cond_level))
        try:
            from deeplearning4j_tpu.utils import blackbox

            blackbox.get_recorder().record_event(
                "health_transition", component=hb.name, frm=tr["from"],
                to=state, stalled_for_seconds=tr["stalled_for_seconds"])
        except Exception:
            logger.exception("flight-recorder transition event failed")
        for fn in listeners:
            try:
                fn(tr)
            except Exception:
                logger.exception("health transition listener failed")


# -- the process-global registry ---------------------------------------------

_HEALTH = HealthRegistry()


def get_health() -> HealthRegistry:
    return _HEALTH
