"""Tests for the round-2 'make every advertised config train what it
claims' work: center loss, layerwise pretraining (AE/VAE/RBM), line-search
optimizers, tbptt_bwd_length, and the ADVICE.md fixes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import (
    AutoEncoder,
    BackpropType,
    CenterLossOutputLayer,
    DenseLayer,
    InputType,
    LSTM,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.conf.layers import RBM, VariationalAutoencoder
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.gradientcheck import check_gradients


def _xy(n=32, nin=8, nout=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, nin)).astype(np.float32)
    y = np.zeros((n, nout), np.float32)
    y[np.arange(n), rng.integers(0, nout, n)] = 1.0
    return x, y


# -- center loss -------------------------------------------------------------

def _center_net(lambda_=0.1, alpha=0.1):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(3)
        .updater(Updater.SGD)
        .learning_rate(0.05)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=8, n_out=6, activation="tanh"))
        .layer(CenterLossOutputLayer(n_in=6, n_out=4, activation="softmax",
                                     loss="mcxent", lambda_=lambda_, alpha=alpha))
        .build()
    ).init()


def test_center_loss_term_in_score():
    """The center term contributes: with centers at 0, score(lambda>0) =
    score(lambda=0) + lambda/2 * mean||f||^2."""
    x, y = _xy()
    net0 = _center_net(lambda_=0.0)
    net1 = _center_net(lambda_=0.5)
    # same params (same seed/arch)
    s0 = net0.score(x, y)
    s1 = net1.score(x, y)
    feats = np.asarray(net0.feed_forward(x)[0])
    expected_pull = 0.5 * float(np.mean(np.sum(feats**2, axis=1)))
    np.testing.assert_allclose(s1 - s0, 0.5 * expected_pull, rtol=1e-4)


def test_center_loss_centers_ema_update():
    x, y = _xy()
    net = _center_net(alpha=0.2)
    before = np.asarray(net.state_list[-1]["centers"]).copy()
    net.fit(x, y, epochs=1, batch_size=32, async_prefetch=False)
    after = np.asarray(net.state_list[-1]["centers"])
    assert np.abs(after - before).max() > 1e-6, "centers were never updated"


def test_center_loss_gradcheck():
    x, y = _xy(8)
    net = _center_net(lambda_=0.1)
    # make centers non-trivial so the pull term has real gradients
    net.state_list[-1]["centers"] = jnp.asarray(
        np.random.default_rng(1).standard_normal((4, 6)).astype(np.float32)
    )
    assert check_gradients(net, x, y, max_checks=60)


def test_center_loss_reduces_intra_class_variance():
    x, y = _xy(64, seed=5)
    net = _center_net(lambda_=1.0, alpha=0.3)
    netp = _center_net(lambda_=0.0)

    def intra_var(n):
        f = np.asarray(n.feed_forward(x)[0])
        cls = y.argmax(1)
        return np.mean([f[cls == k].var(axis=0).sum()
                        for k in range(4) if (cls == k).any()])

    for _ in range(30):
        net.fit(x, y, epochs=1, batch_size=64, async_prefetch=False)
        netp.fit(x, y, epochs=1, batch_size=64, async_prefetch=False)
    assert intra_var(net) < intra_var(netp), (
        "center loss should compact class clusters vs plain training"
    )


# -- pretraining -------------------------------------------------------------

def _recon_mse(conf_layer, params, x):
    from deeplearning4j_tpu.nn.layers.core import autoencoder_reconstruct
    from deeplearning4j_tpu.nn.layers.registry import LayerContext

    recon = autoencoder_reconstruct(conf_layer, params, jnp.asarray(x),
                                    LayerContext(training=False), corrupt=False)
    return float(jnp.mean((recon - x) ** 2))


def test_autoencoder_pretrain_improves_reconstruction():
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(1).updater(Updater.ADAM).learning_rate(0.01).weight_init("xavier")
        .list()
        .layer(AutoEncoder(n_in=10, n_out=5, activation="sigmoid",
                           corruption_level=0.2, loss="mse"))
        .layer(OutputLayer(n_in=5, n_out=3, activation="softmax"))
        .build()
    ).init()
    rng = np.random.default_rng(0)
    x = rng.random((64, 10)).astype(np.float32)
    before = _recon_mse(net.layer_confs[0], net.params_list[0], x)
    net.pretrain_layer(0, x, epochs=40, batch_size=64)
    after = _recon_mse(net.layer_confs[0], net.params_list[0], x)
    assert after < before * 0.8, (before, after)


def test_vae_pretrain_improves_elbo():
    from deeplearning4j_tpu.nn.layers.special import vae_elbo

    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(2).updater(Updater.ADAM).learning_rate(0.005).weight_init("xavier")
        .list()
        .layer(VariationalAutoencoder(
            n_in=10, n_out=4, activation="tanh",
            encoder_layer_sizes=[16], decoder_layer_sizes=[16]))
        .layer(OutputLayer(n_in=4, n_out=3, activation="softmax"))
        .build()
    ).init()
    rng = np.random.default_rng(1)
    x = (rng.random((64, 10)) > 0.5).astype(np.float32)
    key = jax.random.PRNGKey(0)
    before = float(jnp.mean(vae_elbo(net.layer_confs[0], net.params_list[0],
                                     jnp.asarray(x), key)))
    net.pretrain_layer(0, x, epochs=40, batch_size=64)
    after = float(jnp.mean(vae_elbo(net.layer_confs[0], net.params_list[0],
                                    jnp.asarray(x), key)))
    assert after < before, (before, after)


def test_rbm_pretrain_improves_reconstruction():
    from deeplearning4j_tpu.nn.layers.rbm import rbm_cd_stats

    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(3).updater(Updater.SGD).learning_rate(0.1).weight_init("xavier")
        .list()
        .layer(RBM(n_in=12, n_out=6, activation="sigmoid"))
        .layer(OutputLayer(n_in=6, n_out=3, activation="softmax"))
        .build()
    ).init()
    rng = np.random.default_rng(2)
    x = (rng.random((64, 12)) > 0.6).astype(np.float32)
    key = jax.random.PRNGKey(9)
    _, before = rbm_cd_stats(net.layer_confs[0], net.params_list[0],
                             jnp.asarray(x), key)
    net.pretrain_layer(0, x, epochs=60, batch_size=64)
    _, after = rbm_cd_stats(net.layer_confs[0], net.params_list[0],
                            jnp.asarray(x), key)
    assert float(jnp.mean(after)) < float(jnp.mean(before)), (
        float(jnp.mean(before)), float(jnp.mean(after))
    )


def test_pretrain_flag_runs_in_fit():
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(1).updater(Updater.ADAM).learning_rate(0.01).weight_init("xavier")
        .list()
        .layer(AutoEncoder(n_in=8, n_out=4, activation="sigmoid", loss="mse"))
        .layer(OutputLayer(n_in=4, n_out=3, activation="softmax"))
        .pretrain(True)
        .build()
    ).init()
    x, y = _xy(32, 8, 3)
    p_before = np.asarray(net.params_list[0]["vb"]).copy()
    net.fit(x, y, epochs=1, batch_size=32, async_prefetch=False)
    p_after = np.asarray(net.params_list[0]["vb"])
    # vb is only touched by the unsupervised path — pretraining really ran
    assert np.abs(p_after - p_before).max() > 0


# -- line-search optimizers --------------------------------------------------

@pytest.mark.parametrize("algo", ["line_gradient_descent", "conjugate_gradient", "lbfgs"])
def test_line_search_optimizers_decrease_loss(algo):
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(4)
        .optimization_algo(algo)
        .learning_rate(0.5)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=8, n_out=12, activation="tanh"))
        .layer(OutputLayer(n_in=12, n_out=4, activation="softmax"))
        .build()
    ).init()
    x, y = _xy(64)
    s0 = net.score(x, y)
    net.fit(x, y, epochs=20, batch_size=64, async_prefetch=False)
    s1 = net.score(x, y)
    # steepest descent converges slower than the curvature-aware methods
    factor = 0.85 if algo == "line_gradient_descent" else 0.7
    assert s1 < s0 * factor, (algo, s0, s1)
    assert net.iteration == 20


def test_unknown_optimization_algo_raises():
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .optimization_algo("newton_raphson")
        .list()
        .layer(DenseLayer(n_in=4, n_out=4, activation="tanh"))
        .layer(OutputLayer(n_in=4, n_out=2, activation="softmax"))
        .build()
    ).init()
    x, y = _xy(8, 4, 2)
    with pytest.raises(ValueError, match="unknown optimization algorithm"):
        net.fit(x, y, epochs=1, batch_size=8, async_prefetch=False)


# -- tbptt backward length ---------------------------------------------------

def _rnn_net(fwd, bwd, seed=6):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(seed).updater(Updater.SGD).learning_rate(0.05).weight_init("xavier")
        .list()
        .layer(LSTM(n_in=5, n_out=7, activation="tanh"))
        .layer(RnnOutputLayer(n_in=7, n_out=3, activation="softmax", loss="mcxent"))
        .backprop_type(BackpropType.TRUNCATED_BPTT)
        .t_bptt_lengths(fwd, bwd)
        .build()
    ).init()


def _rnn_data(n=8, t=12, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, t, 5)).astype(np.float32)
    y = np.zeros((n, t, 3), np.float32)
    y[np.arange(n)[:, None], np.arange(t)[None, :], rng.integers(0, 3, (n, t))] = 1.0
    return x, y


def test_tbptt_bwd_shorter_than_fwd_trains():
    x, y = _rnn_data()
    net = _rnn_net(fwd=6, bwd=3)
    net.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)
    assert net.iteration == 2  # 12 / 6 segments
    assert np.isfinite(float(net._score))
    # gradients differ from the full-backward variant: the truncation is real
    net_full = _rnn_net(fwd=6, bwd=6)
    net_full.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)
    diffs = [
        np.abs(np.asarray(a[k]) - np.asarray(b[k])).max()
        for a, b in zip(net.params_list, net_full.params_list)
        for k in a
    ]
    assert max(diffs) > 1e-7


def test_tbptt_bwd_equal_fwd_unchanged():
    x, y = _rnn_data(seed=3)
    n1 = _rnn_net(fwd=4, bwd=4)
    n2 = _rnn_net(fwd=4, bwd=4)
    n1.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)
    n2.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)
    for a, b in zip(n1.params_list, n2.params_list):
        for k in a:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


# -- ADVICE.md fixes ---------------------------------------------------------

def test_ff_to_rnn_preprocessor_2d_input():
    """Feed-forward 2-D input into an LSTM via the auto-inserted
    FeedForwardToRnnPreProcessor treats rows as single timesteps (the
    config the builder itself constructs must run)."""
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(1).updater(Updater.SGD).learning_rate(0.05).weight_init("xavier")
        .list()
        .layer(DenseLayer(n_out=6, activation="tanh"))
        .layer(LSTM(n_out=5, activation="tanh"))
        .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(4))
        .build()
    ).init()
    x = np.random.default_rng(0).standard_normal((8, 4)).astype(np.float32)
    out = net.output(x)
    assert out.shape == (8, 1, 2)


def test_output_training_flag_honored():
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(2).updater(Updater.SGD).learning_rate(0.05).weight_init("xavier")
        .dropout(0.5)
        .list()
        .layer(DenseLayer(n_in=8, n_out=32, activation="tanh"))
        .layer(OutputLayer(n_in=32, n_out=4, activation="softmax"))
        .build()
    ).init()
    x, _ = _xy(16)
    inference = np.asarray(net.output(x, training=False))
    train_mode = np.asarray(net.output(x, training=True))
    assert np.abs(inference - train_mode).max() > 1e-6, (
        "training=True must activate dropout"
    )
    # and both modes are deterministic call-to-call
    np.testing.assert_array_equal(inference, np.asarray(net.output(x)))
    np.testing.assert_array_equal(train_mode, np.asarray(net.output(x, training=True)))
