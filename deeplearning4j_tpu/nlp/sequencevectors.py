"""SequenceVectors — the generic embedding trainer.

Analog of the reference's models/sequencevectors/SequenceVectors.java
(1,218 LoC): build vocab over a sequence stream, Huffman-code it, then
train a lookup table with a pluggable learning algorithm. The reference
spawns VectorCalculationsThread workers that push batched updates into
native aggregate ops (:285-289); here the host streams fixed-shape
batches (batching.py) into one jitted device step (learning.py) — the
thread fan-out is unnecessary because the device consumes batches far
faster than one host thread produces them.

Learning algorithms (reference: models/embeddings/learning/impl/):
elements = "skipgram" | "cbow"; sequence (documents) = "dm" | "dbow" are
driven by ParagraphVectors on top of this trainer.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Iterable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.batching import (
    BatchPlan,
    generate_batches,
    group_batches,
    keep_probabilities,
    subsample,
)
from deeplearning4j_tpu.nlp.learning import make_embedding_scan_step
from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.vocab import Huffman, VocabCache, VocabConstructor

logger = logging.getLogger("deeplearning4j_tpu.nlp")


@dataclasses.dataclass
class VectorsConfiguration:
    """Hyperparameters (reference: models/embeddings/loader/
    VectorsConfiguration.java + SequenceVectors.Builder defaults)."""

    layer_size: int = 100
    window: int = 5
    min_word_frequency: int = 5
    iterations: int = 1          # passes per batch stream (reference: iterations)
    epochs: int = 1
    learning_rate: float = 0.025
    min_learning_rate: float = 1e-4
    negative: int = 0
    use_hierarchic_softmax: bool = True
    sampling: float = 0.0        # subsampling threshold t (0 = off)
    batch_size: int = 2048
    scan_size: int = 16          # batches per device call (dispatch amortization)
    seed: int = 12345
    elements_learning_algorithm: str = "skipgram"  # or "cbow"
    # GloVe-specific (reference: GloVe.java builder defaults)
    x_max: float = 100.0
    glove_alpha: float = 0.75
    glove_symmetric: bool = True
    glove_shuffle: bool = True


class SequenceVectors:
    """Generic trainer over sequences of string elements."""

    def __init__(self, conf: VectorsConfiguration,
                 sequences: Optional[Iterable[Sequence[str]]] = None,
                 vocab: Optional[VocabCache] = None):
        self.conf = conf
        self._sequences = sequences
        self.vocab = vocab
        self.lookup: Optional[InMemoryLookupTable] = None
        self.huffman: Optional[Huffman] = None
        self._rng = np.random.default_rng(conf.seed)
        self._base_key = None  # created lazily (jax init) in train paths

    # -- vocab + table construction ------------------------------------------

    def build_vocab(self):
        if self.vocab is None:
            if self._sequences is None:
                raise ValueError("no sequences to build a vocab from")
            self.vocab = VocabConstructor(
                self.conf.min_word_frequency
            ).build(self._sequences)
        if self.vocab.num_words() == 0:
            raise ValueError(
                "empty vocabulary — lower min_word_frequency or supply "
                "more data"
            )
        if self.conf.use_hierarchic_softmax:
            self.huffman = Huffman(self.vocab)
        self.lookup = InMemoryLookupTable(
            self.vocab, self.conf.layer_size, seed=self.conf.seed,
            use_hs=self.conf.use_hierarchic_softmax,
            negative=self.conf.negative,
        )
        return self

    # -- training ------------------------------------------------------------

    def _index_sentences(self, sequences) -> List[np.ndarray]:
        """Token sequences -> vocab-index arrays (unknown words dropped,
        exactly as the reference skips non-vocab elements)."""
        by_word = self.vocab._by_word
        out = []
        for seq in sequences:
            idx = [by_word[t].index for t in seq if t in by_word]
            out.append(np.asarray(idx, np.int64))
        return out

    def fit_file(self, path: str, lowercase: bool = False):
        """Train straight from a text file (newline = sentence) through
        the NATIVE corpus pipeline (native/corpus.cpp — the C++
        VocabConstructor/text-pipeline analog): tokenize, count, sort and
        index entirely outside Python, then stream the indexed sentences
        into the device step. Falls back to the Python tokenizer/vocab
        when no C++ toolchain is available."""
        from deeplearning4j_tpu import native as native_mod

        if not native_mod.native_available():
            logger.warning("native corpus pipeline unavailable; "
                           "falling back to Python tokenization")
            import re

            # match corpus.cpp exactly: ASCII whitespace split and A-Z
            # lowercasing only — the same file must produce the same
            # vocab with or without a C++ toolchain
            ascii_lower = str.maketrans(
                "ABCDEFGHIJKLMNOPQRSTUVWXYZ",
                "abcdefghijklmnopqrstuvwxyz")
            split = re.compile("[ \t\r\n\x0b\x0c]+").split
            with open(path) as f:
                seqs = []
                for line in f:
                    if lowercase:
                        line = line.translate(ascii_lower)
                    seqs.append([t for t in split(line) if t])
            return self.fit(seqs)
        with native_mod.NativeCorpus(path, lowercase=lowercase) as corpus:
            self._vocab_from_native(corpus)  # huffman + lookup over it
            indexed = corpus.indexed_sentences(self.conf.min_word_frequency)
        self.train_indexed(indexed)
        return self

    def _vocab_from_native(self, corpus):
        """Adopt a NativeCorpus vocabulary and build the lookup table."""
        words, counts = corpus.vocab(self.conf.min_word_frequency)
        vocab = VocabCache()
        for w, c in zip(words, counts):
            vocab.add(w, int(c))
        self.vocab = vocab
        self.build_vocab()

    def fit(self, sequences: Optional[Iterable[Sequence[str]]] = None):
        """Build vocab (if needed) and train (reference:
        SequenceVectors.fit :187)."""
        seqs = sequences if sequences is not None else self._sequences
        if self.vocab is None or self.lookup is None:
            if self.vocab is None:
                self._sequences = list(seqs)
                seqs = self._sequences
            self.build_vocab()
        indexed = self._index_sentences(seqs)
        self.train_indexed(indexed)
        return self

    def train_indexed(self, indexed: List[np.ndarray]):
        conf = self.conf
        mode = conf.elements_learning_algorithm
        if mode not in ("skipgram", "cbow"):
            raise ValueError(
                f"unknown elements learning algorithm {mode!r} "
                "(skipgram | cbow)"
            )
        if (mode == "skipgram" and conf.negative > 0
                and not conf.use_hierarchic_softmax):
            # corpus-resident path: upload 4 bytes/word, generate pairs
            # ON DEVICE (nlp/devicegen.py) — the host link is the word2vec
            # bottleneck on remote TPUs (~50 bytes/word of pair batches at
            # ~2.8 MB/s measured vs one corpus upload)
            return self._train_corpus_device(indexed)
        return self._train_batched(indexed)

    def _unigram_dev(self):
        """Device-resident negative-sampling table, uploaded ONCE per
        lookup table (it is 4 MB — re-shipping it every train call through
        a slow host link costs more than a whole epoch). Keyed on the
        lookup instance: build_vocab creates a fresh lookup, so a vocab
        rebuild invalidates the cache rather than sampling stale indices."""
        cached = getattr(self, "_unigram_dev_cache", None)
        if cached is None or cached[0] is not self.lookup:
            table = jnp.asarray(self.lookup.unigram_table().astype(np.int32))
            self._unigram_dev_cache = (self.lookup, table)
        return self._unigram_dev_cache[1]

    def _train_corpus_device(self, indexed: List[np.ndarray]):
        import jax

        from deeplearning4j_tpu.nlp.devicegen import (
            make_corpus_skipgram_step,
            pack_corpus,
        )

        conf = self.conf
        if self._base_key is None:
            self._base_key = jax.random.PRNGKey(conf.seed ^ 0x5EED)
        if getattr(self, "_corpus_step", None) is None:
            self._corpus_step = make_corpus_skipgram_step(
                negative=conf.negative, window=conf.window,
                pairs_per_batch=conf.batch_size)
        step = self._corpus_step
        unigram_dev = self._unigram_dev()
        keep = keep_probabilities(self.vocab.counts(), conf.sampling)
        per_word = conf.window + 1  # E[pairs/word] under the dynamic window
        total_pairs = float(max(
            sum(int(s.size) for s in indexed) * conf.epochs
            * conf.iterations * per_word, 1))
        syn0 = self.lookup.syn0
        syn1neg = self.lookup.syn1neg
        seen = jnp.zeros((), jnp.float32)
        loss = None
        self.last_loss = float("nan")
        for epoch in range(conf.epochs):
            sents = [subsample(s, keep, self._rng) for s in indexed]
            corpus = jnp.asarray(pack_corpus(sents, conf.window))
            for it in range(conf.iterations):
                syn0, syn1neg, loss, seen = step(
                    syn0, syn1neg, unigram_dev, corpus,
                    jnp.float32(conf.learning_rate),
                    jnp.float32(conf.min_learning_rate),
                    jnp.float32(total_pairs), seen,
                    jax.random.fold_in(
                        self._base_key, epoch * 7919 + it),
                )
            if loss is not None:
                self.last_loss = float(loss)
            logger.info("epoch %d done, loss %.4f", epoch, self.last_loss)
        self.lookup.syn0 = syn0
        self.lookup.syn1neg = syn1neg
        return None

    def _train_batched(self, indexed: List[np.ndarray]):
        conf = self.conf
        mode = conf.elements_learning_algorithm
        plan = BatchPlan(
            batch_size=conf.batch_size,
            context_size=1 if mode == "skipgram" else 2 * conf.window,
            hs_arrays=self.huffman.arrays() if self.huffman else None,
            negative=conf.negative,
            device_negatives=conf.negative > 0,
            skip_h_mask=mode == "skipgram",
        )
        unigram_dev = (
            self._unigram_dev()
            if conf.negative > 0 else jnp.zeros((1,), jnp.int32)
        )
        import jax

        if self._base_key is None:
            self._base_key = jax.random.PRNGKey(conf.seed ^ 0x5EED)
        # one jitted step per model — recreating it would discard the
        # compile cache on every train_indexed call
        if getattr(self, "_scan_step", None) is None:
            self._scan_step = make_embedding_scan_step(
                use_hs=conf.use_hierarchic_softmax, negative=conf.negative,
                with_doc=False,
            )
        step = self._scan_step
        keep = keep_probabilities(self.vocab.counts(), conf.sampling)
        # distinct placeholder buffers — donation forbids passing the same
        # array for two donated args
        dummy = lambda: jnp.zeros((1, conf.layer_size), jnp.float32)
        syn0, syn1, syn1neg = (
            self.lookup.syn0,
            self.lookup.syn1 if self.lookup.syn1 is not None else dummy(),
            self.lookup.syn1neg if self.lookup.syn1neg is not None else dummy(),
        )
        doc = dummy()

        # LR decays linearly over expected EXAMPLES: skip-gram emits about
        # (window+1) pairs per word (dynamic window E[w]=(window+1)/2,
        # two sides), cbow one example per word
        per_word = (conf.window + 1) if mode == "skipgram" else 1
        total_examples = max(
            sum(int(s.size) for s in indexed) * conf.epochs
            * conf.iterations * per_word, 1,
        )
        seen = 0
        loss = None
        self.last_loss = float("nan")
        for epoch in range(conf.epochs):
            sents = [
                subsample(s, keep, self._rng) for s in indexed
            ]
            for _ in range(conf.iterations):
                for group, lrs, n_rows in group_batches(
                    generate_batches(
                        iter(sents), plan, window=conf.window, mode=mode,
                        rng=self._rng,
                    ),
                    plan, conf.scan_size,
                    lambda s: max(
                        conf.learning_rate * (1.0 - (seen + s) / total_examples),
                        conf.min_learning_rate,
                    ),
                ):
                    syn0, syn1, syn1neg, doc, loss = step(
                        syn0, syn1, syn1neg, doc, unigram_dev, group, lrs,
                        jax.random.fold_in(self._base_key, seen),
                    )
                    seen += n_rows
            if loss is not None:
                self.last_loss = float(loss)
            logger.info("epoch %d done, loss %.4f", epoch, self.last_loss)
        self.lookup.syn0 = syn0
        if self.lookup.syn1 is not None:
            self.lookup.syn1 = syn1
        if self.lookup.syn1neg is not None:
            self.lookup.syn1neg = syn1neg

    # -- query API (reference: WordVectors interface) ------------------------

    def word_vector(self, word: str):
        return self.lookup.vector(word)

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def similarity(self, a: str, b: str) -> float:
        return self.lookup.similarity(a, b)

    def words_nearest(self, word_or_vec, top_n: int = 10):
        return self.lookup.words_nearest(word_or_vec, top_n)
