"""Abortable queue operations — the sanctioned shape for thread handoffs.

The concurrency lint (analysis/lint.py, CC002) rejects bare `q.put(x)` /
`q.get()` in thread code: a blocking call with no timeout wedges forever
when the peer thread dies, which is exactly the leak class PR 4 fixed by
hand in the data pipeline (data/iterators._put_abortable). These helpers
are the same poll-loop pattern, factored for the non-pipeline users
(serving collector/dispatcher, paramserver push client, UI remote
router): block in short timeouts and re-check an abort predicate between
polls, so a dead peer turns into a QueueAborted instead of a hung
thread.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional, Union

POLL_SECONDS = 0.25

AbortLike = Union[None, threading.Event, Callable[[], bool]]


class QueueAborted(RuntimeError):
    """An abortable queue op's abort predicate fired before the op
    completed — the peer is gone (or shutdown was requested)."""


def _as_predicate(abort: AbortLike) -> Optional[Callable[[], bool]]:
    if abort is None:
        return None
    if isinstance(abort, threading.Event):
        return abort.is_set
    return abort


def get_abortable(q: "queue.Queue", abort: AbortLike = None,
                  poll: float = POLL_SECONDS):
    """Blocking `q.get()` that re-checks `abort` every `poll` seconds.
    Raises QueueAborted when the predicate fires while the queue is
    empty; items already queued always win over the abort."""
    pred = _as_predicate(abort)
    while True:
        try:
            return q.get(timeout=poll)
        except queue.Empty:
            if pred is not None and pred():
                raise QueueAborted("queue get aborted")


def put_abortable(q: "queue.Queue", item, abort: AbortLike = None,
                  poll: float = POLL_SECONDS) -> None:
    """Blocking `q.put(item)` that re-checks `abort` every `poll`
    seconds. Raises QueueAborted when the predicate fires while the
    queue is still full (backpressure is preserved; only a dead/closed
    peer aborts the put)."""
    pred = _as_predicate(abort)
    while True:
        try:
            q.put(item, timeout=poll)
            return
        except queue.Full:
            if pred is not None and pred():
                raise QueueAborted("queue put aborted")
