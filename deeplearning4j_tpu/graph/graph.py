"""In-memory graph (reference: graph/api/IGraph.java + graph/graph/
Graph.java — vertex set with adjacency lists, directed or undirected,
optional edge weights)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class Graph:
    def __init__(self, num_vertices: int, directed: bool = False):
        self.num_vertices = int(num_vertices)
        self.directed = bool(directed)
        self._adj: List[List[int]] = [[] for _ in range(num_vertices)]
        self._w: List[List[float]] = [[] for _ in range(num_vertices)]

    def add_edge(self, a: int, b: int, weight: float = 1.0) -> None:
        if not (0 <= a < self.num_vertices and 0 <= b < self.num_vertices):
            raise ValueError(f"edge ({a},{b}) out of range")
        self._adj[a].append(b)
        self._w[a].append(float(weight))
        if not self.directed:
            self._adj[b].append(a)
            self._w[b].append(float(weight))

    @classmethod
    def from_edge_list(cls, num_vertices: int,
                       edges: Sequence[Tuple[int, int]],
                       directed: bool = False) -> "Graph":
        g = cls(num_vertices, directed)
        for e in edges:
            g.add_edge(e[0], e[1], e[2] if len(e) > 2 else 1.0)
        return g

    def neighbors(self, v: int) -> List[int]:
        return self._adj[v]

    def degree(self, v: int) -> int:
        return len(self._adj[v])

    def weights(self, v: int) -> List[float]:
        return self._w[v]

    def random_neighbor(self, v: int, rng: np.random.Generator,
                        weighted: bool = False) -> Optional[int]:
        nbrs = self._adj[v]
        if not nbrs:
            return None
        if weighted:
            w = np.asarray(self._w[v])
            return int(rng.choice(nbrs, p=w / w.sum()))
        return int(nbrs[rng.integers(0, len(nbrs))])
