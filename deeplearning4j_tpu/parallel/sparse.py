"""Overlapped sparse-embedding pipeline over the sharded paramserver.

The perf thesis (PAPER.md layers 3-4, the Aeron PS + embeddings stack):
sparse pull/push latency is pure overhead unless it is hidden under the
dense jitted step — the same overlap argument the input pipeline proved
for host->device staging (data/iterators.DevicePrefetchIterator) and the
sharded trainer proved for gradient all-reduce. This module is the
client-side layer that does the hiding:

  dedup      per batch, ids collapse to uniques (`np.unique` + inverse
             gather) before touching cache or wire — repeated ids in a
             batch cost one row (`paramserver_pull_rows_coalesced_total`)
  cache      a bounded hot-id LRU (zipf traffic: a few thousand hot rows
             absorb most pulls; hits never go to the wire), write-through
             invalidated on push so cached rows track the server exactly
  prefetch   the NEXT batch's rows resolve one step ahead on a
             `dl4j-sparse-prefetch` worker, so the wire round trip for
             step k+1 overlaps the dense jitted step k
  coherence  pushes are coalesced (per-id delta sums) and applied
             write-through to the cache AND to every unconsumed
             prefetch op — f32 `+=` exactly mirrors the server's
             accumulate, so the training trajectory is byte-identical
             pipeline-on vs pipeline-off (pinned by test, f32 wire)

Coherence protocol (why lookups stay exact under async prefetch):
pushes originate ONLY from the training thread, so a consume (lookup)
never races a push. The resolve worker's wire pull is the one racy read;
it is fenced two ways: (1) flush-elision — before pulling, the worker
flushes the push queue ONLY when the miss set intersects the set of
rows with possibly-in-flight pushes (`_outstanding`), which zipf tail
misses almost never do, keeping the overlap win; (2) any row pushed
while its op is still resolving is marked DIRTY and invalidated from
the cache — at consume time dirty rows are re-pulled synchronously
after a flush, which is authoritative because no pushes can be in
flight while the training thread sits in lookup. Rows parked for
replay (endpoint down) are the failover path and excluded from the
exactness claim, same as the client's own staleness contract.

Books: `paramserver_pull_rows_total == paramserver_cache_hit_total +
paramserver_cache_miss_total` holds exactly (per unique row per
lookup); `sparse_pull_stall_seconds` is the wait the prefetch failed
to hide. Pull wall time books per tenant under the paramserver tier
(utils/resourcemeter.note_ps_pull). `deadline_ms` caps a lookup even
when rows come from cache — a wedged resolve (chaos `hang` on the
`paramserver_rpc` faultpoint) surfaces as TimeoutError at the caller,
not a silent stall.
"""

from __future__ import annotations

import json
import logging
import queue
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import resourcemeter as _resourcemeter
from deeplearning4j_tpu.utils import tracing as _tracing
from deeplearning4j_tpu.utils.concurrency import (
    QueueAborted,
    get_abortable,
    put_abortable,
)

logger = logging.getLogger("deeplearning4j_tpu")

# conftest's thread-leak guard matches this prefix: a pipeline that
# leaves its worker behind fails the owning test, not a later one
SPARSE_THREAD_PREFIX = "dl4j-sparse"


class _Op:
    """One submitted batch: classification snapshot + resolution state.
    All mutable fields are guarded by the pipeline lock except `event`."""

    __slots__ = ("key", "uniq", "inv", "n_raw", "hit_vals", "miss",
                 "miss_set", "fetched", "dirty", "resolved", "error",
                 "event", "ctx")

    def __init__(self, key, uniq, inv, n_raw, hit_vals, miss):
        self.key = key
        self.uniq = uniq
        self.inv = inv
        self.n_raw = n_raw
        self.hit_vals: Dict[int, np.ndarray] = hit_vals
        self.miss: List[int] = miss
        self.miss_set = set(miss)
        self.fetched: Dict[int, np.ndarray] = {}
        self.dirty: set = set()
        self.resolved = False
        self.error: Optional[BaseException] = None
        self.event = threading.Event()
        self.ctx = _tracing.current_context()


class SparseEmbeddingPipeline:
    """Cache-fronted, prefetching pull/push front-end for ONE table on
    an EmbeddingPSClient. Single training thread assumed (the same
    contract as the client's push queue). Use as a context manager or
    call `close()` — the worker thread must not outlive the pipeline."""

    def __init__(self, client, table: str, dim: Optional[int] = None,
                 cache_rows: int = 4096, prefetch: bool = True,
                 prefetch_depth: int = 2,
                 deadline_ms: Optional[float] = None,
                 flush_timeout: float = 30.0,
                 tenant: Optional[str] = None):
        self.client = client
        self.table = table
        self.dim = dim
        self.cache_rows = max(0, int(cache_rows))
        self.prefetch_enabled = bool(prefetch)
        self.deadline_ms = deadline_ms
        self.flush_timeout = float(flush_timeout)
        self.tenant = tenant if tenant is not None else getattr(
            client, "tenant", None)
        self._lock = threading.Lock()
        self._lru: "OrderedDict[int, int]" = OrderedDict()  # id -> slot
        self._free: List[int] = list(range(self.cache_rows))
        self._slab: Optional[np.ndarray] = None  # [cache_rows, dim] f32
        self._ops: Deque[_Op] = deque()  # submitted, unconsumed (FIFO)
        # rows with a possibly not-yet-landed push: the flush-elision set
        self._outstanding: set = set()
        self._closed = False
        # wire pull wall times (resolve + sync fallback) — the bench
        # reads percentiles from here
        self.pull_seconds: Deque[float] = deque(maxlen=8192)
        # plain-int books (the metrics below mirror them): the smoke
        # gate asserts rows == hits + misses without registry scraping
        self.n_rows = 0
        self.n_hit = 0
        self.n_miss = 0
        self.n_coalesced = 0
        self.n_flush_forced = 0
        self.n_flush_elided = 0
        self.n_dirty_fixes = 0
        reg = _metrics.get_registry()
        self._m_rows = reg.counter(
            "paramserver_pull_rows_total",
            "unique rows requested through the sparse pipeline",
            ("table",)).labels(table)
        self._m_coalesced = reg.counter(
            "paramserver_pull_rows_coalesced_total",
            "duplicate ids collapsed by per-batch dedup (rows that never "
            "cost cache or wire)", ("table",)).labels(table)
        self._m_hit = reg.counter(
            "paramserver_cache_hit_total",
            "unique rows served from the hot-id cache", ("table",)
        ).labels(table)
        self._m_miss = reg.counter(
            "paramserver_cache_miss_total",
            "unique rows that went to the wire", ("table",)).labels(table)
        self._m_stall = reg.histogram(
            "sparse_pull_stall_seconds",
            "training-thread wait for rows the prefetch did not hide")
        self._wq: "queue.Queue[_Op]" = queue.Queue(
            maxsize=max(1, int(prefetch_depth)))
        self._stop = threading.Event()
        self._hb = None
        self._worker: Optional[threading.Thread] = None
        if self.prefetch_enabled:
            # liveness: a resolve wedged on a dead endpoint flips
            # component_health{component=sparse_prefetch} to degraded
            self._hb = _health.get_health().register(
                "sparse_prefetch", stall_after=60.0)
            self._worker = threading.Thread(
                target=self._worker_loop, daemon=True,
                name=f"{SPARSE_THREAD_PREFIX}-prefetch")
            self._worker.start()

    # -- cache (all _locked helpers assume self._lock held) ------------------

    def _cache_insert_locked(self, rid: int, val: np.ndarray) -> None:
        if self.cache_rows <= 0:
            return
        if self._slab is None:
            self._slab = np.zeros((self.cache_rows, val.shape[-1]),
                                  np.float32)
        slot = self._lru.get(rid)
        if slot is None:
            if self._free:
                slot = self._free.pop()
            else:  # evict least-recently-used
                _, slot = self._lru.popitem(last=False)
            self._lru[rid] = slot
        else:
            self._lru.move_to_end(rid)
        self._slab[slot] = val

    def _cache_invalidate_locked(self, rid: int) -> None:
        slot = self._lru.pop(rid, None)
        if slot is not None:
            self._free.append(slot)

    def cache_len(self) -> int:
        with self._lock:
            return len(self._lru)

    # -- submit --------------------------------------------------------------

    def _make_op_locked(self, ids: np.ndarray) -> _Op:
        uniq, inv = np.unique(ids, return_inverse=True)
        hit_vals: Dict[int, np.ndarray] = {}
        miss: List[int] = []
        for rid in uniq.tolist():
            slot = self._lru.get(rid)
            if slot is None:
                miss.append(rid)
            else:
                self._lru.move_to_end(rid)
                # snapshot NOW: eviction between submit and consume must
                # not lose the row; write-through keeps it server-exact
                hit_vals[rid] = self._slab[slot].copy()
        return _Op(ids.tobytes(), uniq, inv, int(ids.size), hit_vals, miss)

    def prefetch(self, ids) -> None:
        """Submit the NEXT batch: classification happens now (under the
        lock, on the training thread), the wire work happens on the
        worker while the caller runs the dense step. No-op with
        prefetch disabled (the synchronous arm)."""
        if not self.prefetch_enabled:
            return
        if self._closed:
            raise RuntimeError("SparseEmbeddingPipeline is closed")
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._lock:
            op = self._make_op_locked(ids)
            self._ops.append(op)
        try:
            put_abortable(self._wq, op, abort=self._stop)
        except QueueAborted:
            with self._lock:
                if op in self._ops:
                    self._ops.remove(op)
            raise RuntimeError("SparseEmbeddingPipeline is closed")

    # -- resolve (worker thread, or inline on the training thread) -----------

    def _resolve(self, op: _Op) -> None:
        try:
            with self._lock:
                miss = list(op.miss)
                need_flush = bool(op.miss_set & self._outstanding)
            with _tracing.span("sparse/resolve", table=self.table,
                               rows=len(miss), flush=need_flush):
                if need_flush and miss:
                    self.n_flush_forced += 1
                    self.client.flush(timeout=self.flush_timeout)
                elif miss:
                    self.n_flush_elided += 1
                if miss:
                    t0 = time.perf_counter()
                    got = self.client.pull(
                        self.table, np.asarray(miss, np.int64),
                        deadline_ms=self.deadline_ms)
                    dt = time.perf_counter() - t0
                    self.pull_seconds.append(dt)
                    _resourcemeter.note_ps_pull(self.tenant, dt)
                    with self._lock:
                        if self.dim is None:
                            self.dim = int(got.shape[1])
                        for j, rid in enumerate(miss):
                            op.fetched[rid] = got[j].copy()
                            # a row pushed mid-pull is indeterminate:
                            # leave it out of the cache, consume re-pulls
                            if rid not in op.dirty:
                                self._cache_insert_locked(rid, got[j])
                        op.resolved = True
                else:
                    with self._lock:
                        op.resolved = True
        except BaseException as e:
            # the training thread re-raises this from lookup(); letting
            # it kill the worker would turn a dead endpoint into a hang
            op.error = e
        finally:
            op.event.set()

    def _worker_loop(self) -> None:
        while True:
            try:
                op = get_abortable(self._wq, abort=self._stop)
            except QueueAborted:
                return
            with self._hb.busy():
                with _tracing.attached_ctx(op.ctx):
                    self._resolve(op)

    # -- consume -------------------------------------------------------------

    def lookup(self, ids, deadline_ms: Optional[float] = None
               ) -> np.ndarray:
        """Rows for `ids` (any shape; returns [n_ids, dim] in order,
        duplicates repeated). Consumes the matching prefetched op when
        one is at the head of the FIFO, else resolves inline. Raises
        TimeoutError past `deadline_ms` (default: the pipeline's) even
        when every row would come from cache — a wedged resolve must
        not stall the step unboundedly."""
        if self._closed:
            raise RuntimeError("SparseEmbeddingPipeline is closed")
        ids = np.asarray(ids, np.int64).reshape(-1)
        if deadline_ms is None:
            deadline_ms = self.deadline_ms
        deadline = (None if deadline_ms is None
                    else time.monotonic() + float(deadline_ms) / 1e3)
        key = ids.tobytes()
        with _tracing.span("sparse/lookup", table=self.table,
                           ids=int(ids.size)):
            op = None
            with self._lock:
                if self._ops and self._ops[0].key == key:
                    op = self._ops.popleft()
            if op is None:
                with self._lock:
                    op = self._make_op_locked(ids)
                t0 = time.perf_counter()
                self._resolve(op)
                self._m_stall.observe(time.perf_counter() - t0)
            else:
                t0 = time.perf_counter()
                while not op.event.is_set():
                    if deadline is not None:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            raise TimeoutError(
                                f"sparse lookup missed deadline_ms="
                                f"{deadline_ms} waiting for prefetch "
                                f"of {len(op.miss)} rows")
                        op.event.wait(min(left, 0.25))
                    else:
                        op.event.wait(0.25)
                self._m_stall.observe(time.perf_counter() - t0)
            if op.error is not None:
                raise op.error
            return self._finish(op, deadline, deadline_ms)

    def _finish(self, op: _Op, deadline, deadline_ms) -> np.ndarray:
        with self._lock:
            dirty = sorted(op.dirty)
        if dirty:
            # authoritative fix-up: the training thread is HERE, so no
            # push can be in flight once the queue flushes — the re-pull
            # is exact. Booked as part of the op's misses (no re-count).
            self.n_dirty_fixes += len(dirty)
            self.client.flush(timeout=self.flush_timeout)
            left_ms = (None if deadline is None
                       else max(1.0, (deadline - time.monotonic()) * 1e3))
            t0 = time.perf_counter()
            got = self.client.pull(self.table,
                                   np.asarray(dirty, np.int64),
                                   deadline_ms=left_ms)
            dt = time.perf_counter() - t0
            self.pull_seconds.append(dt)
            _resourcemeter.note_ps_pull(self.tenant, dt)
            with self._lock:
                for j, rid in enumerate(dirty):
                    op.fetched[rid] = got[j].copy()
                    self._cache_insert_locked(rid, got[j])
        n_uniq = int(op.uniq.size)
        if n_uniq == 0:
            d = self.dim if self.dim is not None else 0
            return np.zeros((0, d), np.float32)
        first = (next(iter(op.hit_vals.values())) if op.hit_vals
                 else op.fetched[op.miss[0]])
        vals = np.empty((n_uniq, first.shape[-1]), np.float32)
        for k, rid in enumerate(op.uniq.tolist()):
            v = op.hit_vals.get(rid)
            vals[k] = v if v is not None else op.fetched[rid]
        # books — hit/miss partition the uniques exactly:
        # pull_rows == cache_hit + cache_miss, always
        self.n_rows += n_uniq
        self.n_hit += len(op.hit_vals)
        self.n_miss += len(op.miss)
        self.n_coalesced += op.n_raw - n_uniq
        self._m_rows.inc(n_uniq)
        self._m_hit.inc(len(op.hit_vals))
        self._m_miss.inc(len(op.miss))
        self._m_coalesced.inc(op.n_raw - n_uniq)
        return vals[op.inv]

    # -- push ----------------------------------------------------------------

    def push(self, ids, deltas) -> None:
        """Coalesce per-id delta sums, write them through the cache and
        every unconsumed prefetch op (f32 `+=`, exactly the server's
        accumulate), then hand ONE deduped batch to the client's async
        push queue. Runs on the training thread; returns immediately."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        deltas = np.asarray(deltas, np.float32)
        deltas = deltas.reshape(ids.size, -1)
        uniq, inv = np.unique(ids, return_inverse=True)
        summed = np.zeros((uniq.size, deltas.shape[1]), np.float32)
        np.add.at(summed, inv, deltas)
        uniq_list = uniq.tolist()
        with _tracing.span("sparse/push", table=self.table,
                           rows=len(uniq_list)):
            with self._lock:
                # all prior pushes landed -> nothing is outstanding any
                # more; shrink the elision set before adding this batch
                if (self.client.queued_pushes() == 0
                        and self.client.pending_pushes() == 0):
                    self._outstanding.clear()
                ops = [o for o in self._ops]
                for j, rid in enumerate(uniq_list):
                    d = summed[j]
                    make_dirty = False
                    for op in ops:
                        if rid in op.hit_vals:
                            op.hit_vals[rid] += d
                        elif rid in op.miss_set:
                            if op.resolved and rid not in op.dirty:
                                op.fetched[rid] += d
                            else:
                                op.dirty.add(rid)
                                make_dirty = True
                    if make_dirty:
                        self._cache_invalidate_locked(rid)
                    else:
                        slot = self._lru.get(rid)
                        if slot is not None:
                            self._slab[slot] += d
                self._outstanding.update(uniq_list)
            self.client.push_async(self.table, uniq, summed)

    # -- lifecycle -----------------------------------------------------------

    def stats(self) -> dict:
        return {
            "pull_rows": self.n_rows,
            "cache_hit": self.n_hit,
            "cache_miss": self.n_miss,
            "coalesced": self.n_coalesced,
            "hit_rate": (self.n_hit / self.n_rows) if self.n_rows else 0.0,
            "flush_forced": self.n_flush_forced,
            "flush_elided": self.n_flush_elided,
            "dirty_fixes": self.n_dirty_fixes,
            "cache_len": self.cache_len(),
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=self.flush_timeout)
            if self._worker.is_alive():
                logger.warning("sparse prefetch worker did not exit in "
                               "%.1fs", self.flush_timeout)
            if self._hb is not None:
                _health.get_health().unregister(self._hb)
        with self._lock:
            pending = list(self._ops)
            self._ops.clear()
        for op in pending:
            if op.error is None and not op.resolved:
                op.error = RuntimeError("SparseEmbeddingPipeline closed "
                                        "with prefetch in flight")
            op.event.set()

    def __enter__(self) -> "SparseEmbeddingPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- smoke (the T1 RECSYS SMOKE gate) ----------------------------------------


def _smoke_arm(init: np.ndarray, batches: List[np.ndarray], *,
               prefetch: bool, cache_rows: int) -> tuple:
    """Train a few pipelined steps against 2 fresh in-process endpoints;
    returns (final table, stats). Deterministic deltas so the two arms
    are comparable bit-for-bit."""
    from deeplearning4j_tpu.parallel.paramserver import (
        EmbeddingParameterServer,
        EmbeddingPSClient,
    )

    servers = [EmbeddingParameterServer({"emb": init.copy()})
               for _ in range(2)]
    ports = [s.start() for s in servers]
    client = EmbeddingPSClient([f"http://127.0.0.1:{p}" for p in ports])
    try:
        pipe = SparseEmbeddingPipeline(
            client, "emb", cache_rows=cache_rows, prefetch=prefetch)
        with pipe:
            if prefetch:
                pipe.prefetch(batches[0])
            for k, ids in enumerate(batches):
                rows = pipe.lookup(ids)
                if prefetch and k + 1 < len(batches):
                    pipe.prefetch(batches[k + 1])
                # deterministic "gradient": shrink every touched row
                pipe.push(ids, (-0.125 * rows).astype(np.float32))
            stats = pipe.stats()
        if not client.flush(timeout=30.0):
            raise RuntimeError("paramserver flush timed out in smoke")
        final = client.pull("emb", np.arange(init.shape[0]))
        return final, stats
    finally:
        client.close()
        for s in servers:
            s.stop()


def smoke() -> dict:
    """Tiny end-to-end check: 2 endpoints, zipf ids, a few pipelined
    steps. Asserts the cache books conserve (pull_rows == cache_hit +
    cache_miss), the prefetch-on trajectory is byte-identical to the
    synchronous one, and no `dl4j-sparse-*` thread survives close()."""
    from deeplearning4j_tpu.data.recsys import zipf_ids

    vocab, dim, steps, batch = 64, 8, 6, 32
    rng = np.random.default_rng(7)
    init = rng.standard_normal((vocab, dim)).astype(np.float32)
    batches = [zipf_ids(batch, vocab, alpha=1.3, seed=100 + k)
               for k in range(steps)]

    on, stats_on = _smoke_arm(init, batches, prefetch=True, cache_rows=32)
    off, stats_off = _smoke_arm(init, batches, prefetch=False,
                                cache_rows=0)

    books_ok = (stats_on["pull_rows"]
                == stats_on["cache_hit"] + stats_on["cache_miss"]
                and stats_off["pull_rows"]
                == stats_off["cache_hit"] + stats_off["cache_miss"]
                and stats_on["pull_rows"] > 0)
    identical = on.tobytes() == off.tobytes()
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith(SPARSE_THREAD_PREFIX)]
    return {
        "ok": bool(books_ok and identical and not leaked),
        "books_ok": books_ok,
        "prefetch_matches_sync": identical,
        "leaked_threads": leaked,
        "pipelined": stats_on,
        "synchronous": stats_off,
    }


def main() -> int:
    report = smoke()
    sys.stdout.write(json.dumps(report, indent=1, default=str) + "\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    # `python -m` runs a SECOND copy of this module as __main__; the
    # smoke must drive the canonical instance the client/metrics import
    from deeplearning4j_tpu.parallel import sparse as _canonical

    sys.exit(_canonical.main())
