"""Continuous-batching autoregressive decode engine — the serving tier
for sequence/decode traffic the one-shot stack (parallel/inference.py)
cannot express.

PAPER.md's layer-3 ParallelInference is strictly one-shot: a request is
a single fused forward. Autoregressive decode is the opposite shape —
each request is a LOOP whose state (the LSTM h/c carry) must survive
between steps, and requests arrive and finish at different times. The
classic server answer (batch whole requests, wait for the longest) idles
the device on every finished-early sequence; the Orca-style answer
implemented here is **iteration-level scheduling** (continuous
batching):

* ONE jitted per-step decode program advances a fixed pool of
  `n_slots` padded slots by one token per dispatch. Per-slot recurrent
  carry stays RESIDENT ON DEVICE across steps (the engine never round-
  trips h/c through the host); the per-row math of the LSTM cell is
  independent across the batch dimension, so slots cannot bleed into
  each other (pinned by the bit-identity test against a sequential
  `rnn_time_step` reference).
* New requests are admitted MID-FLIGHT into free slots: slot init is a
  masked in-graph scatter (`carry.at[idx].set(0)`) under its own
  shape-keyed jitted program — admission never retraces, so the compile
  count is O(1) in traffic (same discipline as the PR 1 bucket caches).
* Finished sequences (EOS / max-len / deadline) free their slot the
  same step; emitted tokens stream back per-request via `on_token`.
* **Zero-downtime weight swap**: `load_version(params)` commits v+1
  onto the device BESIDE v on the caller's thread (transfer +
  block_until_ready — the step loop never waits on it), then the engine
  flips its param reference atomically between steps and v drains by
  garbage collection. Compile-free by construction: the step program is
  keyed on shapes, and params are an ARGUMENT of the jitted fn, never a
  captured constant (`serving_weight_swap_total` + a `decode/swap` span
  record every flip).
* **Multi-tenant admission**: per-tenant deadline defaults and
  weighted-fair slot allocation (stride scheduling over per-tenant
  virtual time) replace FIFO at this tier; per-tenant admit/shed books
  ride the shared `AdmissionBooks` (parallel/inference.py) and obey the
  PR 8 conservation law `admitted == completed + shed + failed` per
  tenant.

Production integration: slots feed the metrics registry
(`decode_slots_in_use`, `decode_tokens_total{tenant}`,
`decode_token_seconds` with trace exemplars), the engine heartbeats the
watchdog (`<prefix>_engine` — a wedged step degrades component health
exactly like a wedged dispatcher), faults inject at the `decode_step`
point (`cli chaos --preset decode`), request lifecycle spans are
`decode/admit` -> `decode/step` -> `decode/emit`, and the REST layer
exposes `POST /generate` (serving/inference_server.py) behind the same
deadline/429 contract as /predict.

The kernel path: the per-step forward reuses `rnn_time_step`'s
internals (`MultiLayerNetwork.rnn_decode_step_fn`), which routes
single-timestep stateful LSTM steps through the inference-only Pallas
step kernel on TPU (`ops/pallas_lstm.lstm_step` — no VJP stashes).
"""

from __future__ import annotations

import argparse
import logging
import math
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.inference import (
    _WAIT_SHED_GRACE,
    _trace_shed_span,
    AdmissionBooks,
    DeadlineExceeded,
    ReplicaUnavailable,
    RequestRejected,
    RequestValidationError,
)
from deeplearning4j_tpu.utils import blackbox as _blackbox
from deeplearning4j_tpu.utils import faultpoints as _faults
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import locktrace as _locktrace
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import resourcemeter as _resourcemeter
from deeplearning4j_tpu.utils import runledger as _runledger
from deeplearning4j_tpu.utils import tenancy as _tenancy
from deeplearning4j_tpu.utils import tracing as _tracing

logger = logging.getLogger("deeplearning4j_tpu")

# how long the engine loop sleeps on its condition when it has nothing
# to do (no active slot, empty queue); a submit notifies it awake, so
# this only bounds wakeup latency for the notify-vs-wait race
_IDLE_WAIT = 0.05

# the shared identity layer's default — one name across every tier
DEFAULT_TENANT = _tenancy.DEFAULT_TENANT


class _Request:
    """One admitted generate() call. Host-side bookkeeping only — the
    recurrent state lives in the engine's device-resident carry."""

    __slots__ = ("prompt", "max_new_tokens", "tenant", "deadline", "fut",
                 "on_token", "ctx", "tokens", "t_submit", "t_decode0",
                 "last_emit")

    def __init__(self, prompt, max_new_tokens, tenant, deadline, on_token,
                 ctx):
        self.prompt = prompt                  # np.int32 [P]
        self.max_new_tokens = max_new_tokens
        self.tenant = tenant
        self.deadline = deadline              # absolute monotonic or None
        self.fut = Future()
        self.on_token = on_token
        self.ctx = ctx                        # tracing SpanContext or None
        self.tokens: List[int] = []           # emitted so far
        self.t_submit = time.perf_counter()
        self.t_decode0 = None                 # first step in a slot
        self.last_emit = None


class _Slot:
    __slots__ = ("req", "pos")

    def __init__(self, req: _Request):
        self.req = req
        self.pos = 0  # prompt tokens fed so far


class DecodeEngine:
    """Continuous-batching decode over a recurrent MultiLayerNetwork
    (charlstm is the first model). The model's input must be one-hot
    token ids and its output head a distribution over the same vocab
    (autoregressive feedback); decoding is greedy argmax, so engine
    output is deterministic and bit-comparable to a sequential
    `rnn_time_step` reference."""

    def __init__(
        self,
        model,
        n_slots: int = 8,
        *,
        eos_token: Optional[int] = None,
        default_max_tokens: int = 64,
        default_deadline_ms: Optional[float] = None,
        tenant_weights: Optional[Dict[str, float]] = None,
        tenant_deadline_ms: Optional[Dict[str, float]] = None,
        queue_capacity: int = 256,
        health_stall_after: float = 30.0,
        component_prefix: str = "decode",
        run_ledger=None,
    ):
        if int(n_slots) < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.model = model
        model._require_init()
        from deeplearning4j_tpu.nn.multilayer import (
            MultiLayerNetwork,
            _is_recurrent,
        )

        if not isinstance(model, MultiLayerNetwork):
            raise ValueError(
                "DecodeEngine needs a MultiLayerNetwork (the decode step "
                "fn is exposed by nn/multilayer)")
        if not any(_is_recurrent(c) for c in model.layer_confs):
            raise ValueError(
                "DecodeEngine needs a recurrent model (LSTM/GravesLSTM "
                "layers carrying streaming state)")
        first = model.layer_confs[0]
        inner = getattr(first, "inner", first)
        self.vocab = int(inner.n_in)
        last = model.layer_confs[-1]
        if int(getattr(last, "n_out", -1)) != self.vocab:
            raise ValueError(
                f"autoregressive decode feeds the output head back as "
                f"input: head n_out={getattr(last, 'n_out', None)} must "
                f"equal input vocab {self.vocab}")
        self.n_slots = int(n_slots)
        self.eos_token = None if eos_token is None else int(eos_token)
        self.default_max_tokens = int(default_max_tokens)
        self.default_deadline_ms = (None if default_deadline_ms is None
                                    else float(default_deadline_ms))
        self.queue_capacity = max(0, int(queue_capacity))
        self.component_prefix = component_prefix
        self._weights = dict(tenant_weights or {})
        self._tenant_deadline_ms = dict(tenant_deadline_ms or {})

        # run-ledger opt-in (same ONE-knob contract as fit/serving)
        self._owned_ledger = self._attached_ledger = None
        if run_ledger is not None:
            if isinstance(run_ledger, str):
                self._owned_ledger = _runledger.RunLedger(run_ledger)
                self._attached_ledger = _runledger.attach(self._owned_ledger)
            else:
                self._attached_ledger = _runledger.attach(run_ledger)

        # -- device-resident state -------------------------------------------
        self._params = model.params_list         # the version the step reads
        self._states = model.state_list
        self._carry = model.rnn_zero_carry(self.n_slots)
        self._version = 0
        self._pending_swap = None                # (version, placed params)
        self._swaps = 0
        # host mirror of the per-slot input token fed next step
        self._feed = np.zeros(self.n_slots, np.int32)

        # -- jitted programs (built lazily; O(1) compiles forever) -----------
        self._step_fn = None
        self._reset_fn = None
        self._fused_steps = 1
        self._step_k = 1

        # -- host scheduling state -------------------------------------------
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._queues: Dict[str, deque] = {}      # tenant -> waiting requests
        self._vtime: Dict[str, float] = {}       # weighted-fair virtual time
        # the scheduler's current virtual position (the vtime of the
        # last tenant served): a tenant re-arriving after an idle spell
        # is clamped UP to it, so idling never banks future share
        self._gvt = 0.0
        self._slots: List[Optional[_Slot]] = [None] * self.n_slots
        self._free: List[int] = list(range(self.n_slots))
        self._books = AdmissionBooks()
        _resourcemeter.register_books(_resourcemeter.TIER_DECODE,
                                      self._books)
        # HBM attribution for the live weight version (keyed per version
        # so a drained one releases its bytes); no-op when unmetered
        self._hbm_src: Optional[str] = None
        self._note_weights_hbm(0, self._params)
        self._requests = 0
        self._steps = 0
        self._tokens_out = 0
        self._draining = False
        self._stopped = threading.Event()

        # -- observability ----------------------------------------------------
        reg = _metrics.get_registry()
        self._m_requests = reg.counter(
            "decode_requests_total",
            "decode requests admitted, by tenant", ("tenant",))
        self._m_tokens = reg.counter(
            "decode_tokens_total",
            "tokens emitted by the decode engine, by tenant", ("tenant",))
        self._m_shed = reg.counter(
            "decode_shed_total",
            "decode requests shed instead of served late, by tenant, "
            "stage and reason", ("tenant", "stage", "reason"))
        self._m_steps = reg.histogram(
            "decode_step_seconds",
            "wall time of one continuous-batching decode step (all "
            "active slots advance one token)").labels()
        self._m_token_lat = reg.histogram(
            "decode_token_seconds",
            "per-token latency of emitted tokens (inter-emit gap; the "
            "first token's gap starts at slot admission)").labels()
        self._m_swaps = reg.counter(
            "serving_weight_swap_total",
            "zero-downtime model version swaps committed by the decode "
            "engine").labels()
        self._g_slots = reg.gauge(
            "decode_slots_in_use",
            "decode slots currently holding an active sequence").labels()
        self._g_slots.set(0)
        self._hb = _health.get_health().register(
            f"{component_prefix}_engine", stall_after=health_stall_after)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"dl4j-decode-engine-{component_prefix}")
        self._thread.start()

    # -- public ----------------------------------------------------------------

    def generate(self, prompt: Sequence[int],
                 max_new_tokens: Optional[int] = None,
                 tenant: str = DEFAULT_TENANT,
                 deadline_ms: Optional[float] = None,
                 on_token=None) -> Future:
        """Submit one autoregressive request. `prompt` is a non-empty
        sequence of token ids (< vocab); the engine feeds it token by
        token (prefill shares steps with decode — iteration-level
        scheduling), then emits up to `max_new_tokens` greedily, stopping
        early at `eos_token`. Returns a Future resolving to the emitted
        token list (EOS included when hit); `on_token(token_id)` is
        called from the engine thread per emitted token — the streaming
        hook the REST layer's chunked /generate rides. `deadline_ms` is
        the request's total budget (falls back to the tenant's default,
        then the engine's): work that cannot make it is SHED
        (DeadlineExceeded / RequestRejected), never served late."""
        _runledger.note_request()
        # canonicalize through the bounded registry: past the cap,
        # unknown names collapse into __other__ (books and spend stay
        # conserved; only the per-name breakdown saturates)
        tenant = _tenancy.intern(tenant)
        try:
            p = np.asarray(prompt, np.int64)
        except (TypeError, ValueError) as e:
            # an un-coercible prompt (string, ragged, null) is the
            # CLIENT's fault: it must map to 400, not a bare ValueError
            # the REST layer reports as a 500 server fault
            raise RequestValidationError(
                f"prompt must be a sequence of token ids: {e}") from None
        if p.ndim != 1 or p.size == 0:
            raise RequestValidationError(
                "prompt must be a non-empty 1-D sequence of token ids")
        if p.min() < 0 or p.max() >= self.vocab:
            raise RequestValidationError(
                f"prompt token ids must be in [0, {self.vocab}), got "
                f"range [{p.min()}, {p.max()}]")
        mx = (self.default_max_tokens if max_new_tokens is None
              else int(max_new_tokens))
        if mx < 1:
            raise RequestValidationError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if deadline_ms is None:
            deadline_ms = self._tenant_deadline_ms.get(
                tenant, self.default_deadline_ms)
        elif not math.isfinite(float(deadline_ms)):
            raise RequestValidationError(
                f"deadline_ms must be finite, got {deadline_ms!r}")
        deadline = (None if deadline_ms is None
                    else time.monotonic() + float(deadline_ms) / 1e3)
        adm_span = _tracing.span("decode/admit", tenant=tenant,
                                 prompt_len=int(p.size))
        with adm_span:
            ctx = _tracing.current_context()
            with self._lock:
                if self._draining:
                    raise ReplicaUnavailable(
                        "DecodeEngine has been shut down")
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    self._shed_locked(tenant, "admission", "expired",
                                      admitted=False)
                    self._trace_shed("admission", "expired", ctx)
                    raise DeadlineExceeded(
                        "deadline expired before admission",
                        stage="admission")
                if self.queue_capacity and self._queued_locked() \
                        >= self.queue_capacity:
                    self._shed_locked(tenant, "admission", "queue_full",
                                      admitted=False)
                    self._trace_shed("admission", "queue_full", ctx)
                    raise RequestRejected(
                        f"decode queue at capacity "
                        f"({self.queue_capacity} requests)",
                        reason="queue_full",
                        retry_after=self._wait_hint_locked())
                req = _Request(p.astype(np.int32), mx, tenant, deadline,
                               on_token, ctx)
                self._requests += 1
                self._books.admit(tenant)
                self._m_requests.labels(tenant).inc()
                q = self._queues.setdefault(tenant, deque())
                if not q:
                    # idle -> busy transition: start at the scheduler's
                    # current position (stride scheduling's start-tag
                    # rule) — a long-idle tenant must not return with a
                    # stale-low vtime and monopolize admissions
                    self._vtime[tenant] = max(
                        self._vtime.get(tenant, self._gvt), self._gvt)
                q.append(req)
                self._wake.notify_all()
        return req.fut

    def generate_sync(self, prompt, **kw) -> List[int]:
        """generate() + a bounded wait. A request with a deadline is
        given up `_WAIT_SHED_GRACE` past it (the engine is the primary
        shedder — this is the wedged-engine backstop, same contract as
        ParallelInference's wait stage)."""
        deadline_ms = kw.get("deadline_ms")
        if deadline_ms is None:
            deadline_ms = self._tenant_deadline_ms.get(
                kw.get("tenant", DEFAULT_TENANT), self.default_deadline_ms)
        fut = self.generate(prompt, **kw)
        if deadline_ms is None:
            return fut.result()
        try:
            return fut.result(
                timeout=float(deadline_ms) / 1e3 + _WAIT_SHED_GRACE)
        except FutureTimeoutError:
            exc = DeadlineExceeded(
                "deadline expired waiting on a stalled decode engine",
                stage="wait")
            if self._fail(fut, exc, kw.get("tenant", DEFAULT_TENANT),
                          outcome="shed", stage="wait", reason="expired"):
                raise exc from None
            return fut.result()

    def load_version(self, params) -> int:
        """Commit a new parameter version BESIDE the live one and ask the
        engine to flip to it between steps. The transfer (device_put per
        leaf onto the live leaf's placement) and the readiness wait run
        on THIS thread — the step loop never blocks on the swap. The
        flip is atomic (one reference assignment between dispatches) and
        compile-free (params are a jit argument; shapes are validated
        here so the program cannot retrace). Returns the new version
        number; the old version drains as soon as the last dispatch
        holding it completes.

        Versions are MONOTONE but not every one serves: concurrent
        loads race for the flip and the latest wins — a version loaded
        while another was still pending is superseded (warned, never
        served). A deployer confirming a rollout must therefore wait
        for `metrics()["version"] >= returned`, not `==`."""
        def place(new, old):
            a = jnp.asarray(np.asarray(new), getattr(old, "dtype", None))
            if a.shape != old.shape:
                raise ValueError(
                    f"load_version shape mismatch: {a.shape} vs live "
                    f"{old.shape} — a swap must not change the program")
            # mirror the live leaf's placement AND committedness: jit
            # caches key on both, and a swap that flips either retraces
            # — the opposite of the compile-free contract
            if getattr(old, "committed", False):
                return jax.device_put(a, old.sharding)
            return a

        placed = jax.tree_util.tree_map(place, params, self._params)
        jax.block_until_ready(placed)
        with self._lock:
            if self._pending_swap is not None:
                # latest wins: a not-yet-flipped pending version is
                # superseded and never serves — loudly, because its
                # load_version caller already holds that version number
                logger.warning(
                    "decode load_version: pending version %d superseded "
                    "before it was served", self._pending_swap[0])
            v = self._version + self._swaps_pending_locked() + 1
            self._pending_swap = (v, placed)
            self._wake.notify_all()
        return v

    def set_fused_steps(self, k: int) -> "DecodeEngine":
        """Scan `k` decode steps into ONE jitted dispatch: the per-slot
        argmax feeds back in-graph, prompt positions stay teacher-forced
        (the host precomputes a [k, slots] force mask per window), and
        the host walks the k returned tokens per slot afterwards —
        admission and EOS/max-len checks happen every k tokens, deadline
        checks stay per engine iteration (one window). Cuts per-token
        dispatch overhead ~k× on dispatch-bound models (see
        `bench.py decode`'s fused arm); emitted tokens are identical to
        k=1 because forcing and feedback reproduce the single-step feed
        exactly. k=1 restores the per-token program."""
        k = int(k)
        if k < 1:
            raise ValueError(f"set_fused_steps needs k >= 1, got {k}")
        with self._lock:
            if k != self._fused_steps:
                self._fused_steps = k
                self._step_fn = None  # rebuilt lazily at the next step
        return self

    def _swaps_pending_locked(self) -> int:
        return 1 if self._pending_swap is not None else 0

    def _note_weights_hbm(self, version: int, params) -> None:
        """Attribute the live weight version's device bytes in the HBM
        gauge (weights serve every tenant, so they book under the shared
        default tenant), keyed per version: committing v releases v-1's
        bytes. Accounted at the flip — the commit-beside window where
        two versions coexist is transient and never metered. One
        module-global read when unmetered."""
        if not _resourcemeter.is_enabled():
            return
        src = f"decode_weights_{id(self)}_v{version}"
        nbytes = sum(int(getattr(a, "nbytes", 0) or 0)
                     for a in jax.tree_util.tree_leaves(params))
        _resourcemeter.note_hbm(DEFAULT_TENANT, src, nbytes)
        old, self._hbm_src = self._hbm_src, src
        if old is not None:
            _resourcemeter.note_hbm(DEFAULT_TENANT, old, 0)

    @property
    def version(self) -> int:
        return self._version

    def program_cache_size(self) -> int:
        """Total jit-cache entries behind the engine (step + slot-reset
        programs). Steady state is 2 after warmup — growth under traffic
        means admission or stepping is retracing, exactly what the
        shape-keyed design forbids (the t1 decode smoke gates on it)."""
        n = 0
        for fn in (self._step_fn, self._reset_fn):
            if fn is not None:
                try:
                    n += fn._cache_size()
                except AttributeError:
                    n += 1  # compiled, size API unavailable: count once
        return n

    def metrics(self) -> dict:
        with self._lock:
            active = sum(1 for s in self._slots if s is not None)
            queued = {t: len(q) for t, q in self._queues.items() if q}
            m = {
                "slots": self.n_slots,
                "slots_in_use": active,
                "queue_depth": sum(queued.values()),
                "queued_by_tenant": queued,
                "requests": self._requests,
                "steps": self._steps,
                "tokens": self._tokens_out,
                "version": self._version,
                "swaps": self._swaps,
                "tenants": self._books.per_tenant(),
                "conservation_ok": self._books.conservation_ok(),
                **self._books.totals(),
            }
        m["program_cache_size"] = self.program_cache_size()
        m["vocab"] = self.vocab
        m["eos_token"] = self.eos_token
        return m

    def shutdown(self, timeout: float = 30.0):
        """Graceful: new submits are refused, everything queued or in a
        slot is served, then the engine thread exits. A wedged engine
        past `timeout` has its remaining futures failed explicitly so no
        caller hangs forever."""
        with self._lock:
            if self._draining:
                already_stopped = self._stopped.is_set()
            else:
                self._draining = True
                already_stopped = False
            self._wake.notify_all()
        if already_stopped:
            return
        self._thread.join(timeout=timeout)
        _health.get_health().unregister(self._hb)
        if self._owned_ledger is not None:
            self._owned_ledger.close()
        elif self._attached_ledger is not None:
            _runledger.detach(self._attached_ledger)
        if self._thread.is_alive():
            err = RuntimeError("DecodeEngine shut down while wedged")
            with self._lock:
                victims = [s.req for s in self._slots if s is not None]
                victims += [r for q in self._queues.values() for r in q]
                for q in self._queues.values():
                    q.clear()
            for req in victims:
                self._fail(req.fut, err, req.tenant)

    # -- books / future plumbing ----------------------------------------------

    def _queued_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _wait_hint_locked(self) -> float:
        """Retry-After hint: a rough time-to-free-slot — queued requests
        ahead × a nominal per-request budget. Deliberately coarse; the
        429 contract only needs a sane backoff hint."""
        return 0.05 * (1.0 + self._queued_locked() / max(1, self.n_slots))

    def _shed_locked(self, tenant, stage, reason, admitted=True):
        self._books.shed(stage, reason, tenant=tenant, admitted=admitted)
        self._m_shed.labels(tenant, stage, reason).inc()

    def _trace_shed(self, stage, reason, ctx):
        _trace_shed_span(stage, reason, ctx)

    def _resolve(self, req: _Request) -> bool:
        """Settle + book under ONE lock hold: whoever's set wins does
        the booking (a waiter's wait-stage shed may race this), and a
        caller resumed by fut.result() cannot read metrics() before the
        completion is booked — metrics() needs the same lock."""
        with self._lock:
            try:
                req.fut.set_result(list(req.tokens))
            except Exception:
                return False
            self._books.complete(req.tenant)
        return True

    def _fail(self, fut: Future, exc: Exception, tenant,
              outcome: str = "failed", stage: Optional[str] = None,
              reason: Optional[str] = None) -> bool:
        with self._lock:
            try:
                fut.set_exception(exc)
            except Exception:
                return False
            if outcome == "shed":
                self._shed_locked(tenant, stage, reason)
            else:
                self._books.fail(tenant)
        return True

    # -- weighted-fair admission ----------------------------------------------

    def _pick_tenant_locked(self) -> Optional[str]:
        """Stride scheduling: among tenants with waiting requests, pick
        the smallest virtual time; admitting charges the tenant
        1/weight. A heavy tenant's vtime advances slowly, so it wins
        more slots — proportional share, never starvation (every
        waiting tenant's vtime is eventually smallest; re-arrivals are
        clamped to the scheduler position at enqueue time)."""
        waiting = [t for t, q in self._queues.items() if q]
        if not waiting:
            return None
        for t in waiting:
            self._vtime.setdefault(t, self._gvt)
        return min(waiting, key=lambda t: (self._vtime[t], t))

    def _admit_locked(self, now: float) -> List[int]:
        """Fill free slots from the tenant queues (shedding anything that
        expired while queued). Returns the slot indices admitted this
        round — their carries are reset OUTSIDE the lock."""
        admitted = []
        while self._free:
            tenant = self._pick_tenant_locked()
            if tenant is None:
                break
            req = self._queues[tenant].popleft()
            if req.fut.done():
                # already settled (a generate_sync waiter shed it at the
                # wait stage while it queued): whoever settled it booked
                # it — booking again would break conservation
                continue
            if req.deadline is not None and now >= req.deadline:
                # set-then-book, inline because the lock is already
                # held: only the winning set books the shed (the waiter
                # backstop races this on its own _fail path)
                try:
                    req.fut.set_exception(DeadlineExceeded(
                        "deadline expired while queued for a slot",
                        stage="queued"))
                except Exception:
                    continue
                self._shed_locked(tenant, "queued", "expired")
                self._trace_shed("queued", "expired", req.ctx)
                continue
            self._gvt = self._vtime.get(tenant, self._gvt)
            self._vtime[tenant] = self._gvt \
                + 1.0 / max(1e-6, float(self._weights.get(tenant, 1.0)))
            idx = self._free.pop()
            self._slots[idx] = _Slot(req)
            self._feed[idx] = req.prompt[0]
            req.t_decode0 = time.perf_counter()
            req.last_emit = req.t_decode0
            admitted.append(idx)
        return admitted

    # -- the engine loop -------------------------------------------------------

    def _build_programs(self):
        base = self.model.rnn_decode_step_fn()
        vocab = self.vocab
        K = self._fused_steps

        def one(params, states, carry, tokens):
            # token ids -> exact one-hot rows (bit-identical to the host
            # one-hot a rnn_time_step caller feeds), one step, greedy
            # argmax folded into the same program
            x = jax.nn.one_hot(tokens, vocab, dtype=jnp.float32)
            new_carry, out = base(params, states, carry, x)
            return new_carry, jnp.argmax(out, axis=-1).astype(jnp.int32)

        donate = (2,) if jax.default_backend() != "cpu" else ()
        if K == 1:
            self._step_fn = jax.jit(one, donate_argnums=donate)
        else:
            def fused(params, states, carry, feed_toks, feed_force):
                # K steps as one scan: teacher-forced positions (prompt
                # prefill; always step 0, whose token the host staged in
                # _feed) take feed_toks, the rest feed the previous
                # argmax back in-graph — the same per-step inputs the
                # k=1 program sees, so tokens are identical
                def body(c, xs):
                    cry, prev = c
                    ftok, force = xs
                    tok = jnp.where(force, ftok, prev)
                    cry, nxt = one(params, states, cry, tok)
                    return (cry, nxt), nxt

                (carry, _), toks = jax.lax.scan(
                    body, (carry, feed_toks[0]), (feed_toks, feed_force))
                return carry, toks  # toks: [K, slots]

            self._step_fn = jax.jit(fused, donate_argnums=donate)
        self._step_k = K  # the K the live program was built for
        self.model._note_compile("decode_step")

        def reset(carry, idx):
            # masked in-graph scatter: zero ONE slot's h/c rows. idx is a
            # traced scalar, so every admission reuses this one program.
            return jax.tree_util.tree_map(
                lambda a: a.at[idx].set(0), carry)

        rdonate = (0,) if jax.default_backend() != "cpu" else ()
        self._reset_fn = jax.jit(reset, donate_argnums=rdonate)
        self.model._note_compile("decode_admit")

    def _step_once(self):
        """One continuous-batching iteration: swap-if-pending, admit,
        advance every active slot one token, emit/finish/shed."""
        # 1. pending weight swap: flip BETWEEN dispatches
        with self._lock:
            pending = self._pending_swap
            self._pending_swap = None
        if pending is not None:
            v, placed = pending
            t0 = time.perf_counter()
            self._params = placed
            with self._lock:
                self._version = v
                self._swaps += 1
            self._m_swaps.inc()
            self._note_weights_hbm(v, placed)
            _tracing.record_complete("decode/swap", t0,
                                     time.perf_counter(), None, version=v)
            _blackbox.get_recorder().record_event(
                "decode_weight_swap", version=v)
            logger.info("decode engine flipped to weight version %d "
                        "(compile-free)", v)
        # 2. admission into free slots
        now = time.monotonic()
        with self._lock:
            admitted = self._admit_locked(now)
            active = [(i, s) for i, s in enumerate(self._slots)
                      if s is not None]
            n_active = len(active)
            draining = self._draining
            idle = n_active == 0 and self._queued_locked() == 0 \
                and self._pending_swap is None
        self._g_slots.set(n_active)
        if idle:
            if draining:
                return False  # drained: the loop exits
            with self._wake:
                self._wake.wait(_IDLE_WAIT)
            return True
        if self._step_fn is None:
            self._build_programs()
        for idx in admitted:
            self._carry = self._reset_fn(self._carry, jnp.int32(idx))
        # 3. ONE jitted step over the whole pool
        t0 = time.perf_counter()
        with self._hb.busy():
            # chaos hook: latency/hang here is a wedged decode step — the
            # watchdog degrades <prefix>_engine and deadline-carrying
            # slots shed on the next iteration; an `error` fails the
            # active sequences (their carry is device state mid-flight —
            # not resumable) and the engine keeps serving
            K = self._step_k
            try:
                _faults.fault_point("decode_step", active=n_active)
                # CN003 probe: the engine must never enter the jitted
                # pool step holding the admission lock (off = one
                # module-global read)
                _locktrace.note_dispatch("decode/step")
                with _tracing.span("decode/step", active=n_active,
                                   version=self._version):
                    if K == 1:
                        self._carry, nxt = self._step_fn(
                            self._params, self._states, self._carry,
                            jnp.asarray(self._feed))
                    else:
                        toks, force = self._fused_feed_window(K, active)
                        self._carry, nxt = self._step_fn(
                            self._params, self._states, self._carry,
                            jnp.asarray(toks), jnp.asarray(force))
                    nxt_host = np.asarray(nxt)
            except BaseException as e:
                self._fail_active(e)
                self._hb.beat()
                return True
        dt = time.perf_counter() - t0
        self._m_steps.observe(dt)
        if _resourcemeter.is_enabled():
            # split this step's wall time over the tenants whose slots
            # it advanced: weighted-fair scheduling becomes auditable
            # device-second SPEND. Shares built only when metered — the
            # unmetered loop pays one module-global read per step.
            shares: Dict[str, int] = {}
            for _, s in active:
                t = s.req.tenant
                shares[t] = shares.get(t, 0) + 1
            _resourcemeter.note_decode_step(dt, shares)
        with self._lock:
            self._steps += 1
        # 4. host bookkeeping per active slot
        now = time.monotonic()
        t_emit = time.perf_counter()
        for idx, slot in active:
            if K == 1:
                self._advance_slot(idx, slot, int(nxt_host[idx]), now,
                                   t_emit)
            else:
                self._advance_slot_fused(idx, slot, nxt_host[:, idx], now,
                                         t_emit)
        self._hb.beat()
        return True

    def _fused_feed_window(self, K: int, active) -> tuple:
        """[K, slots] token + force matrices for one fused window: step 0
        is always forced with the staged `_feed`; later steps force the
        prompt token a slot will have reached at that step (prefill), and
        everything else feeds back the in-graph argmax."""
        toks = np.zeros((K, self.n_slots), np.int32)
        force = np.zeros((K, self.n_slots), bool)
        toks[0] = self._feed
        force[0] = True
        for idx, slot in active:
            prompt = slot.req.prompt
            P = len(prompt)
            for t in range(1, K):
                if slot.pos + t < P:
                    toks[t, idx] = prompt[slot.pos + t]
                    force[t, idx] = True
        return toks, force

    def _advance_slot_fused(self, idx: int, slot: _Slot, toks, now: float,
                            t_emit: float):
        """Walk one slot through the K tokens of a fused window —
        the same per-step transitions as _advance_slot (prefill
        consumes prompt positions, the rest emit), applied K at a time.
        Tokens computed past EOS/max-len are discarded host-side (the
        device ran them; the slot's carry resets at its next admission).
        The per-token latency histogram spreads the window gap evenly
        over the window's emissions so ITL stays comparable across K."""
        req = slot.req
        if req.fut.done():
            self._free_slot(idx)
            return
        P = len(req.prompt)
        emitted = []
        done = False
        for t in range(len(toks)):
            if slot.pos < P:
                slot.pos += 1
                if slot.pos < P:
                    continue  # still prefilling: this step's output is
                              # ignored (teacher forcing)
            token = int(toks[t])
            req.tokens.append(token)
            emitted.append(token)
            if req.on_token is not None:
                try:
                    req.on_token(token)
                except Exception:
                    logger.exception("decode on_token callback raised "
                                     "(request continues)")
            if (len(req.tokens) >= req.max_new_tokens
                    or (self.eos_token is not None
                        and token == self.eos_token)):
                done = True
                break
        if emitted:
            tr = req.ctx.trace_id if req.ctx is not None else None
            gap = (t_emit - req.last_emit) / len(emitted)
            for _ in emitted:
                self._m_token_lat.observe(gap, trace_id=tr,
                                          tenant=req.tenant)
            req.last_emit = t_emit
            self._m_tokens.labels(req.tenant).inc(len(emitted))
            _resourcemeter.note_tokens(req.tenant, len(emitted))
            with self._lock:
                self._tokens_out += len(emitted)
        if done:
            if req.ctx is not None and _tracing.is_enabled():
                _tracing.record_complete(
                    "decode/emit", req.t_decode0, time.perf_counter(),
                    req.ctx, tenant=req.tenant, tokens=len(req.tokens))
            self._free_slot(idx)
            self._resolve(req)
            return
        # stage the next window's step-0 feed: the next prompt token
        # while prefilling, else the last emitted token (feedback)
        self._feed[idx] = (req.prompt[slot.pos] if slot.pos < P
                           else emitted[-1])
        self._check_deadline(idx, slot, now)

    def _advance_slot(self, idx: int, slot: _Slot, token: int, now: float,
                      t_emit: float):
        req = slot.req
        if req.fut.done():
            # the waiter already shed it (wait-stage backstop): free the
            # slot without touching the books — whoever failed it booked
            self._free_slot(idx)
            return
        P = len(req.prompt)
        if slot.pos < P:
            slot.pos += 1
            if slot.pos < P:
                # still prefilling: feed the next prompt token, ignore
                # the model's prediction (teacher forcing)
                self._feed[idx] = req.prompt[slot.pos]
                self._check_deadline(idx, slot, now)
                return
        # the fed token was the last prompt token or a generated one:
        # `token` is the next emitted token
        req.tokens.append(token)
        self._feed[idx] = token
        tr = req.ctx.trace_id if req.ctx is not None else None
        self._m_token_lat.observe(t_emit - req.last_emit, trace_id=tr,
                                  tenant=req.tenant)
        req.last_emit = t_emit
        self._m_tokens.labels(req.tenant).inc()
        _resourcemeter.note_tokens(req.tenant, 1)
        with self._lock:
            self._tokens_out += 1
        if req.on_token is not None:
            try:
                req.on_token(token)
            except Exception:
                logger.exception("decode on_token callback raised "
                                 "(request continues)")
        done = (len(req.tokens) >= req.max_new_tokens
                or (self.eos_token is not None and token == self.eos_token))
        if done:
            if req.ctx is not None and _tracing.is_enabled():
                _tracing.record_complete(
                    "decode/emit", req.t_decode0, time.perf_counter(),
                    req.ctx, tenant=req.tenant, tokens=len(req.tokens))
            self._free_slot(idx)
            self._resolve(req)
            return
        self._check_deadline(idx, slot, now)

    def _check_deadline(self, idx: int, slot: _Slot, now: float):
        req = slot.req
        if req.deadline is None or now < req.deadline:
            return
        self._free_slot(idx)
        if self._fail(req.fut,
                      DeadlineExceeded(
                          "deadline expired mid-decode "
                          f"({len(req.tokens)} token(s) emitted)",
                          stage="decode"),
                      req.tenant, outcome="shed", stage="decode",
                      reason="expired"):
            self._trace_shed("decode", "expired", req.ctx)

    def _free_slot(self, idx: int):
        with self._lock:
            self._slots[idx] = None
            self._free.append(idx)
        self._feed[idx] = 0

    def _fail_active(self, exc: BaseException):
        """A failed step dispatch loses every active sequence (their
        carry was mid-flight in the failed program); queued work is
        untouched and the engine keeps serving."""
        with self._lock:
            victims = [(i, s) for i, s in enumerate(self._slots)
                       if s is not None]
        for idx, slot in victims:
            self._free_slot(idx)
            self._fail(slot.req.fut,
                       RuntimeError(f"decode step failed: "
                                    f"{type(exc).__name__}: {exc}"),
                       slot.req.tenant)
        # the carry may hold donated/poisoned buffers after a failed
        # dispatch: rebuild it so the next admission starts clean
        self._carry = self.model.rnn_zero_carry(self.n_slots)
        logger.warning("decode step failed (%s); %d active sequence(s) "
                       "failed, engine continues", exc, len(victims))

    def _loop(self):
        _blackbox.get_recorder().record_event(
            "decode_engine_start", slots=self.n_slots)
        try:
            while True:
                if not self._step_once():
                    break
        except BaseException:
            logger.exception("decode engine loop died")
            with self._lock:
                self._draining = True
            self._fail_active(RuntimeError("decode engine died"))
            with self._lock:
                victims = [r for q in self._queues.values() for r in q]
                for q in self._queues.values():
                    q.clear()
            for req in victims:
                self._fail(req.fut, RuntimeError("decode engine died"),
                           req.tenant)
        finally:
            self._stopped.set()
            _blackbox.get_recorder().record_event("decode_engine_stop")


# -- t1 gate: the decode smoke ------------------------------------------------


def smoke(n_slots: int = 4, vocab: int = 13, hidden: int = 16,
          requests: int = 10) -> dict:
    """Tiny end-to-end proof for scripts/t1.sh: a charlstm decode engine
    with 2 tenants serves mixed prompts through ONE mid-run weight swap;
    asserts every request completes, the per-tenant books conserve, and
    the program cache stays at its warmup size (zero retraces across
    admissions and the swap). Raises on any violation; returns the
    verdict dict."""
    from deeplearning4j_tpu.models.charlstm import char_lstm_network

    net = char_lstm_network(vocab_size=vocab, hidden=hidden, layers=1,
                            tbptt_length=8)
    eng = DecodeEngine(net, n_slots=n_slots,
                       tenant_weights={"a": 3.0, "b": 1.0},
                       default_max_tokens=6, component_prefix="t1_decode")
    try:
        rng = np.random.default_rng(0)
        # warmup: one request compiles the step + reset programs
        eng.generate([1, 2], max_new_tokens=2, tenant="a").result(60)
        warm = eng.program_cache_size()
        futs = []
        for i in range(requests):
            prompt = rng.integers(0, vocab, size=1 + i % 4).tolist()
            futs.append(eng.generate(prompt, max_new_tokens=3 + i % 3,
                                     tenant="a" if i % 2 else "b"))
            if i == requests // 2:
                v = eng.load_version(jax.tree_util.tree_map(
                    lambda a: a * 1.001, net.params_list))
        outs = [f.result(60) for f in futs]
        m = eng.metrics()
        ok_swap = m["swaps"] == 1 and m["version"] == v
        ok_books = m["conservation_ok"] and \
            m["completed"] == requests + 1 and m["shed"] == 0 \
            and m["failed"] == 0
        ok_cache = eng.program_cache_size() == warm
        verdict = {
            "requests": requests,
            "tokens": m["tokens"],
            "swap_ok": ok_swap,
            "books_ok": ok_books,
            "tenants": m["tenants"],
            "program_cache": {"warm": warm,
                              "final": eng.program_cache_size()},
            "zero_retraces": ok_cache,
            "ok": bool(ok_swap and ok_books and ok_cache
                       and all(len(o) >= 3 for o in outs)),
        }
        if not verdict["ok"]:
            raise AssertionError(f"decode smoke violated: {verdict}")
        return verdict
    finally:
        eng.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="decode engine smoke (the scripts/t1.sh gate)")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args(argv)
    if not args.smoke:
        ap.error("only --smoke is supported")
    import json

    # operator surface: announce through the package logger (library
    # code never prints — lint CC006), same as the server mains
    from deeplearning4j_tpu import configure_logging

    if all(isinstance(h, logging.NullHandler) for h in logger.handlers):
        configure_logging()
    v = smoke()
    logger.info("decode smoke: %s", json.dumps(v))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
