"""Staged input pipeline: multi-worker ETL, device-resident prefetch,
on-device transforms, iterator edge cases, and the shutdown contract
(close-on-break — the AsyncDataSetIterator worker-leak regression).

Equivalence pin: training with the pipeline on must be byte-identical to
training with it off (same seeds, CPU) — staging moves WHERE work runs,
never WHAT runs.
"""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    PIPELINE_THREAD_PREFIX,
    AsyncDataSetIterator,
    ExistingDataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
    StackedDataSetIterator,
)
from deeplearning4j_tpu.data.prefetch import (
    DevicePrefetchIterator,
    ParallelDataSetIterator,
)
from deeplearning4j_tpu.data.transforms import DeviceBatchTransform


def _live_pipeline_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(PIPELINE_THREAD_PREFIX) and t.is_alive()]


def _assert_no_pipeline_threads(timeout=2.0):
    deadline = time.monotonic() + timeout
    while _live_pipeline_threads() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not _live_pipeline_threads(), [
        t.name for t in _live_pipeline_threads()]


def _toy_dataset(n=24, n_in=4, n_out=2, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n_in)).astype(np.float32)
    y = np.zeros((n, n_out), np.float32)
    y[np.arange(n), rng.integers(0, n_out, n)] = 1.0
    return DataSet(x, y)


def _toy_net(n_in=4, n_out=2, seed=42):
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


# -- satellite 1: AsyncDataSetIterator close-on-break -------------------------


def test_async_iterator_break_mid_epoch_stops_worker():
    """Regression: breaking out of iteration used to leave the producer
    thread blocked forever on the full queue (no shutdown signal)."""
    ds = _toy_dataset(n=64)
    it = AsyncDataSetIterator(ListDataSetIterator(ds, 2), queue_size=1)
    for i, _ in enumerate(it):
        if i == 1:
            break  # queue is full, producer is blocked in put()
    _assert_no_pipeline_threads()


def test_async_iterator_context_manager_and_close():
    ds = _toy_dataset(n=64)
    with AsyncDataSetIterator(ListDataSetIterator(ds, 2), queue_size=1) as it:
        gen = iter(it)
        next(gen)
        it.close()  # explicit close with the epoch still live
    _assert_no_pipeline_threads()


def test_async_iterator_consumer_exception_stops_worker():
    ds = _toy_dataset(n=64)
    it = AsyncDataSetIterator(ListDataSetIterator(ds, 2), queue_size=1)
    with pytest.raises(RuntimeError, match="consumer died"):
        for _ in it:
            raise RuntimeError("consumer died")
    _assert_no_pipeline_threads()


def test_async_iterator_full_epoch_and_producer_error():
    ds = _toy_dataset(n=12)
    assert len(list(AsyncDataSetIterator(ListDataSetIterator(ds, 3)))) == 4

    class Bad:
        def __iter__(self):
            yield DataSet(np.zeros((2, 4), np.float32),
                          np.zeros((2, 2), np.float32))
            raise OSError("source broke")

        def reset(self):
            pass

    with pytest.raises(OSError, match="source broke"):
        list(AsyncDataSetIterator(Bad()))
    _assert_no_pipeline_threads()


# -- multi-worker ETL ---------------------------------------------------------


def test_parallel_etl_ordered_reassembly():
    """Workers finish out of order (adversarial per-item delays); ordered
    mode must still emit base order, each item exactly once."""
    items = list(range(16))

    def tf(i):
        time.sleep(0.005 * ((17 - i) % 5))
        return DataSet(np.full((2, 3), i, np.float32),
                       np.zeros((2, 1), np.float32))

    out = [int(b.features[0, 0])
           for b in ParallelDataSetIterator(items, transform=tf, workers=4)]
    assert out == items
    _assert_no_pipeline_threads()


def test_parallel_etl_unordered_is_complete():
    items = list(range(16))
    tf = lambda i: DataSet(np.full((1, 2), i, np.float32),
                           np.zeros((1, 1), np.float32))
    it = ParallelDataSetIterator(items, transform=tf, workers=4,
                                 ordered=False)
    assert sorted(int(b.features[0, 0]) for b in it) == items
    _assert_no_pipeline_threads()


def test_parallel_etl_transform_error_surfaces_in_order():
    items = list(range(10))

    def bad(i):
        if i == 5:
            raise ValueError("decode failed")
        return DataSet(np.full((1, 2), i, np.float32),
                       np.zeros((1, 1), np.float32))

    got = []
    with pytest.raises(ValueError, match="decode failed"):
        for b in ParallelDataSetIterator(items, transform=bad, workers=3):
            got.append(int(b.features[0, 0]))
    # ordered mode: everything before the failed position was delivered
    assert got == [0, 1, 2, 3, 4]
    _assert_no_pipeline_threads()


def test_parallel_etl_close_mid_stream_and_reuse():
    items = list(range(64))
    tf = lambda i: DataSet(np.full((1, 2), i, np.float32),
                           np.zeros((1, 1), np.float32))
    it = ParallelDataSetIterator(items, transform=tf, workers=3,
                                 queue_size=3)
    for i, _ in enumerate(it):
        if i == 2:
            break  # workers blocked on the small full queue
    _assert_no_pipeline_threads()
    # a fresh epoch over a fresh base works after the aborted one
    it2 = ParallelDataSetIterator(list(range(6)), transform=tf, workers=2)
    assert len(list(it2)) == 6
    _assert_no_pipeline_threads()


def test_parallel_etl_feeds_fit():
    ds = _toy_dataset(n=24)
    batches = ListDataSetIterator(ds, 4)
    # identity-transform ETL in front of the full staged pipeline
    it = ParallelDataSetIterator(list(batches), transform=None, workers=2)
    net = _toy_net()
    net.fit(it, epochs=1, async_prefetch=True)
    assert net.iteration == 6
    _assert_no_pipeline_threads()


# -- device prefetch ----------------------------------------------------------


def test_device_prefetch_preplaces_and_marks():
    import jax

    ds = _toy_dataset(n=12)
    out = list(DevicePrefetchIterator(ListDataSetIterator(ds, 3), depth=2))
    assert len(out) == 4
    assert all(isinstance(b.features, jax.Array) for b in out)
    assert all(getattr(b, "_pipeline_staged", False) for b in out)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b.features) for b in out]), ds.features)
    _assert_no_pipeline_threads()


def test_device_prefetch_runs_placement_in_worker_thread():
    seen_threads = []

    def placement(ds):
        seen_threads.append(threading.current_thread().name)
        return ds

    ds = _toy_dataset(n=8)
    list(DevicePrefetchIterator(ListDataSetIterator(ds, 2), depth=1,
                                placement=placement))
    assert len(seen_threads) == 4
    assert all(n.startswith(PIPELINE_THREAD_PREFIX) for n in seen_threads)
    _assert_no_pipeline_threads()


def test_staged_batch_not_transformed_twice_by_fit_loop():
    """The fit loop must skip `_batch_transform` for batches the pipeline
    already staged — one application total, in the worker thread."""
    calls = []

    def counting_transform(ds):
        calls.append(threading.current_thread().name)
        return ds

    net = _toy_net()
    net._batch_transform = counting_transform
    net.fit(ListDataSetIterator(_toy_dataset(n=16), 4), epochs=1,
            async_prefetch=True)
    assert len(calls) == 4
    assert all(n.startswith(PIPELINE_THREAD_PREFIX) for n in calls)
    _assert_no_pipeline_threads()


def test_fit_error_mid_epoch_leaves_no_workers():
    from deeplearning4j_tpu.data.iterators import DataSetIterator

    class Bad(DataSetIterator):
        def __iter__(self):
            d = _toy_dataset(n=4)
            yield DataSet(d.features, d.labels)
            raise OSError("iterator bug")

    net = _toy_net()
    with pytest.raises(OSError, match="iterator bug"):
        net.fit(Bad(), epochs=1, async_prefetch=True)
    _assert_no_pipeline_threads()


def test_cross_thread_close_unblocks_consumer():
    """close() from another thread must end iteration, not leave the
    consumer blocked in q.get() (the producer can never deliver its
    sentinel once stop is set)."""

    from deeplearning4j_tpu.data.iterators import DataSetIterator

    class Slow(DataSetIterator):
        def __iter__(self):
            d = _toy_dataset(n=2)
            yield d
            time.sleep(1.0)  # consumer blocks waiting for the next batch
            yield d

    it = AsyncDataSetIterator(Slow(), queue_size=1)
    consumed = []

    def consume():
        for b in it:
            consumed.append(b)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.2)  # consumer is now blocked on the empty queue
    it.close()
    t.join(timeout=1.0)
    assert not t.is_alive(), "consumer stayed blocked after close()"
    assert len(consumed) == 1
    _assert_no_pipeline_threads()


def test_user_prefetch_iterator_must_carry_net_transforms():
    """A caller-built DevicePrefetchIterator that does not apply the
    net's configured staging is an error, not silent wrong training."""
    ds = _toy_dataset(n=16)
    tr = DeviceBatchTransform(normalize=(0.0, 1.0))
    net = _toy_net().set_input_transform(tr)
    with pytest.raises(ValueError, match="input transform"):
        net.fit(DevicePrefetchIterator(ListDataSetIterator(ds, 4)),
                epochs=1, async_prefetch=True)
    # built WITH the transform, the same pipeline is accepted
    net.fit(DevicePrefetchIterator(ListDataSetIterator(ds, 4), transform=tr),
            epochs=1, async_prefetch=True)
    assert net.iteration == 4
    _assert_no_pipeline_threads()


def test_user_prefetch_with_mesh_sharding_accepted():
    """The error message's own advice must work: a caller-built pipeline
    whose placement is the mesh plan's shard function is accepted (bound
    methods are fresh objects per access — equality, not identity)."""
    from deeplearning4j_tpu.parallel import data_parallel_mesh

    net = _toy_net()
    net.set_mesh(data_parallel_mesh())
    it = DevicePrefetchIterator(
        ListDataSetIterator(_toy_dataset(n=32), 16),
        placement=net._mesh_plan.shard_batch)
    net.fit(it, epochs=1)
    assert net.iteration == 2
    _assert_no_pipeline_threads()


# -- the tentpole equivalence pin ---------------------------------------------


def test_fit_byte_identical_prefetch_on_vs_off():
    ds = _toy_dataset(n=48, seed=3)
    nets = {}
    for on in (False, True):
        net = _toy_net(seed=11)
        net.fit(ListDataSetIterator(ds, 8), epochs=3, async_prefetch=on)
        assert net.iteration == 18
        nets[on] = net
    for a, b in zip(nets[False].params_list, nets[True].params_list):
        assert set(a) == set(b)
        for k in a:
            assert np.asarray(a[k]).tobytes() == np.asarray(b[k]).tobytes()
    s_off = float(np.asarray(nets[False]._score))
    s_on = float(np.asarray(nets[True]._score))
    assert s_off == s_on  # exact, not allclose
    _assert_no_pipeline_threads()


def test_fit_epochs_restage_with_device_prefetch():
    """Each epoch re-runs __iter__ on the pipeline: fresh workers, same
    data — multi-epoch fits must work and clean up."""
    net = _toy_net()
    it = DevicePrefetchIterator(
        ListDataSetIterator(_toy_dataset(n=16), 4), depth=2)
    net.fit(it, epochs=3, async_prefetch=True)
    assert net.iteration == 12
    _assert_no_pipeline_threads()


# -- on-device transforms -----------------------------------------------------


def test_device_transform_normalize_matches_numpy():
    mean, std = 0.25, 2.0
    t = DeviceBatchTransform(normalize=(mean, std))
    x = np.random.default_rng(0).random((6, 5)).astype(np.float32)
    out = np.asarray(t(DataSet(x, np.zeros((6, 1), np.float32))).features)
    np.testing.assert_allclose(out, (x - mean) / std, rtol=1e-6)


def test_device_transform_deterministic_and_shape_keyed():
    t1 = DeviceBatchTransform(random_flip=True, random_crop=2, seed=9)
    t2 = DeviceBatchTransform(random_flip=True, random_crop=2, seed=9)
    rng = np.random.default_rng(1)
    img = DataSet(rng.random((4, 8, 8, 3)).astype(np.float32),
                  np.zeros((4, 1), np.float32))
    a = np.asarray(t1(img).features)
    b = np.asarray(t2(img).features)
    np.testing.assert_array_equal(a, b)  # same seed+step: identical
    c = np.asarray(t1(img).features)
    assert not np.array_equal(a, c)  # next step: fresh augmentation
    assert t1.compile_count == 1  # same shape: one trace
    img2 = DataSet(rng.random((2, 8, 8, 3)).astype(np.float32),
                   np.zeros((2, 1), np.float32))
    t1(img2)
    assert t1.compile_count == 2  # new shape: second trace
    t2.reset_steps()
    np.testing.assert_array_equal(np.asarray(t2(img).features), a)


def test_device_transform_rejects_augment_on_non_images():
    t = DeviceBatchTransform(random_flip=True)
    with pytest.raises(ValueError, match="NHWC"):
        t(DataSet(np.zeros((4, 10), np.float32),
                  np.zeros((4, 1), np.float32)))


def test_device_transform_identical_in_pipeline_and_inline():
    """Same transform object, same batch order: fit results must be
    byte-identical whether the transform runs in the prefetch worker
    (pipeline on) or inline (pipeline off)."""
    ds = _toy_dataset(n=32, seed=5)
    results = []
    for on in (False, True):
        net = _toy_net(seed=13)
        net.set_input_transform(DeviceBatchTransform(normalize=(0.1, 1.5)))
        net.fit(ListDataSetIterator(ds, 8), epochs=2, async_prefetch=on)
        results.append([{k: np.asarray(v).tobytes() for k, v in p.items()}
                        for p in net.params_list])
    assert results[0] == results[1]
    _assert_no_pipeline_threads()


# -- satellite 2: _ds_examples ------------------------------------------------


def test_ds_examples_counts_unknown_sizes_explicitly():
    from deeplearning4j_tpu.utils.metrics import get_registry

    net = _toy_net()
    unknown = net._fit_obs()["examples_unknown"]
    before = unknown.value

    class NoCount:
        pass

    assert net._ds_examples(NoCount()) == 0
    assert unknown.value == before + 1
    # real example counts unaffected
    assert net._ds_examples(_toy_dataset(n=7)) == 7
    assert unknown.value == before + 1


def test_ds_examples_no_longer_swallows_real_bugs():
    net = _toy_net()

    class Buggy:
        def num_examples(self):
            raise RuntimeError("corrupted shard")

    with pytest.raises(RuntimeError, match="corrupted shard"):
        net._ds_examples(Buggy())


# -- satellite 3: iterator edge cases -----------------------------------------


def test_multiple_epochs_iterator_reset_semantics():
    ds = _toy_dataset(n=12)
    base = ListDataSetIterator(ds, 4)
    it = MultipleEpochsIterator(3, base)
    assert len(list(it)) == 9  # 3 epochs x 3 batches
    # a second pass resets the base each epoch and yields the same count
    assert len(list(it)) == 9
    # and it composes with the async stage
    assert len(list(AsyncDataSetIterator(it, queue_size=2))) == 9
    _assert_no_pipeline_threads()


def test_stacked_iterator_ragged_tail():
    ds = _toy_dataset(n=20)
    base = ListDataSetIterator(ds, 4)  # 5 batches of 4
    it = StackedDataSetIterator(base, 2)
    sizes = [b.num_examples() for b in it]
    assert sizes == [8, 8, 4]  # ragged tail = the leftover single batch
    total = np.concatenate(
        [np.asarray(b.features) for b in StackedDataSetIterator(base, 2)])
    np.testing.assert_array_equal(total, ds.features)
    assert it.batch_size() == 8
    assert it.total_examples() == 20


def test_stacked_iterator_k_larger_than_stream():
    ds = _toy_dataset(n=8)
    it = StackedDataSetIterator(ListDataSetIterator(ds, 4), 5)
    sizes = [b.num_examples() for b in it]
    assert sizes == [8]  # everything collapses into one (ragged) stack


def test_existing_iterator_with_pipeline_stages():
    ds = _toy_dataset(n=8)
    batches = ListDataSetIterator(ds, 2)
    it = DevicePrefetchIterator(
        AsyncDataSetIterator(ExistingDataSetIterator(list(batches)), 2),
        depth=1)
    assert len(list(it)) == 4
    assert len(list(it)) == 4  # re-iterable
    _assert_no_pipeline_threads()
